"""pintk: the Tk interactive-timing GUI.

Reference: pint/pintk/ (plk.py:1610 plk widget, paredit.py par editor,
timedit.py tim editor, pintk.py shell). The reference couples its state
machine to the widgets; here the widgets are a THIN shell over the
headless `interactive.InteractivePulsar` session and the matplotlib
`plot_utils.InteractivePlot` front end — every button routes through the
same methods a script or notebook would call, so the GUI adds wiring, not
logic (and the whole workflow stays testable headless).

Layout:
- left column: fitter choice, Fit / Undo / Reset / write-par / write-tim,
  a color-mode selector, the wrms readout, and the free-parameter
  checkbox panel (fit flags; reference plk.py par panel);
- right: the embedded matplotlib canvas with the plk rectangle selection
  and single-key bindings (d/j/f/u/r/c/+/-, plot_utils.InteractivePlot);
- Par... / Tim... buttons open editor windows (Text widget + Apply /
  Revert / Save, reference paredit.py / timedit.py): Apply rebuilds the
  model (or reloads the TOAs) from the edited text through the normal
  parsing path, as an undoable operation.

Run: ``pintk model.par toas.tim`` (or ``python -m pint_tpu.pintk``).
"""

from __future__ import annotations

import argparse
import sys

from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.pintk")


def default_toolkit():
    """The real Tk widget toolkit, bundled for injection.

    PintkApp builds its whole widget tree through this namespace (tk,
    ttk, filedialog, the TkAgg canvas classes, Figure), so a headless
    test can substitute a fake toolkit and exercise every line of the
    GUI wiring without an X display (tests/test_interactive.py
    TestPintkShell) — the widgets stay a thin shell, and the shell
    itself is CI-executed."""
    from types import SimpleNamespace

    import tkinter as tk
    from tkinter import filedialog, ttk

    import matplotlib

    matplotlib.use("TkAgg", force=False)
    from matplotlib.backends.backend_tkagg import (
        FigureCanvasTkAgg,
        NavigationToolbar2Tk,
    )
    from matplotlib.figure import Figure

    return SimpleNamespace(
        tk=tk, ttk=ttk, filedialog=filedialog,
        FigureCanvasTkAgg=FigureCanvasTkAgg,
        NavigationToolbar2Tk=NavigationToolbar2Tk, Figure=Figure,
    )


class PintkApp:
    """Main window wiring (constructed around a live Tk root; every
    action delegates to the InteractivePulsar session)."""

    FITTERS = ("auto", "wls", "gls", "downhill_wls", "downhill_gls")
    COLOR_MODES = ("none", "obs", "fe-flag")

    def __init__(self, session, master=None, toolkit=None):
        self.toolkit = toolkit or default_toolkit()
        tk, ttk = self.toolkit.tk, self.toolkit.ttk
        FigureCanvasTkAgg = self.toolkit.FigureCanvasTkAgg
        NavigationToolbar2Tk = self.toolkit.NavigationToolbar2Tk
        Figure = self.toolkit.Figure

        from pint_tpu.plot_utils import InteractivePlot

        self.session = session
        self.root = master or tk.Tk()
        self.root.title(f"pintk — {session.name}")

        left = ttk.Frame(self.root)
        left.pack(side=tk.LEFT, fill=tk.Y, padx=4, pady=4)

        # fitter choice
        ttk.Label(left, text="Fitter").pack(anchor="w")
        self.fitter_var = tk.StringVar(value=session.fit_method)
        ttk.OptionMenu(left, self.fitter_var, session.fit_method,
                       *self.FITTERS, command=self._set_fitter).pack(
            anchor="w", fill=tk.X)

        # action buttons
        for label, cmd in (
            ("Fit", self.do_fit), ("Undo", self.do_undo),
            ("Reset", self.do_reset), ("Clear selection", self.do_clear),
            ("Delete selected", self.do_delete),
            ("Jump selected", self.do_jump),
            ("Write par...", self.do_write_par),
            ("Write tim...", self.do_write_tim),
            ("Par...", self.open_par_editor),
            ("Tim...", self.open_tim_editor),
        ):
            ttk.Button(left, text=label, command=cmd).pack(
                anchor="w", fill=tk.X, pady=1)

        ttk.Label(left, text="Color by").pack(anchor="w", pady=(6, 0))
        self.color_var = tk.StringVar(value="none")
        ttk.OptionMenu(left, self.color_var, "none", *self.COLOR_MODES,
                       command=lambda *_: self.refresh()).pack(
            anchor="w", fill=tk.X)

        self.status = tk.StringVar(value="")
        ttk.Label(left, textvariable=self.status, wraplength=180).pack(
            anchor="w", pady=(6, 0))

        # free-parameter checkboxes (scrollable)
        ttk.Label(left, text="Fit parameters").pack(anchor="w", pady=(6, 0))
        canvas = tk.Canvas(left, width=180, height=320)
        scroll = ttk.Scrollbar(left, orient="vertical", command=canvas.yview)
        self.param_frame = ttk.Frame(canvas)
        self.param_frame.bind(
            "<Configure>",
            lambda e: canvas.configure(scrollregion=canvas.bbox("all")),
        )
        canvas.create_window((0, 0), window=self.param_frame, anchor="nw")
        canvas.configure(yscrollcommand=scroll.set)
        canvas.pack(side=tk.LEFT, fill=tk.Y)
        scroll.pack(side=tk.LEFT, fill=tk.Y)
        self.param_vars: dict = {}
        self._build_param_panel()

        # the plk canvas
        fig = Figure(figsize=(9, 6), dpi=100)
        ax = fig.add_subplot(111)
        self.canvas = FigureCanvasTkAgg(fig, master=self.root)
        self.plot = InteractivePlot(session, ax=ax)
        self.plot.connect()
        NavigationToolbar2Tk(self.canvas, self.root)
        self.canvas.get_tk_widget().pack(side=tk.RIGHT, fill=tk.BOTH,
                                         expand=True)
        self.canvas.draw()
        self._update_status()

    # --- panels ---------------------------------------------------------------

    def _build_param_panel(self):
        tk, ttk = self.toolkit.tk, self.toolkit.ttk

        for child in list(self.param_frame.children.values()):
            child.destroy()
        self.param_vars.clear()
        meta = self.session.model.param_meta
        for name in sorted(meta, key=lambda n: (len(n), n)):
            m = meta[name]
            if getattr(m.spec, "kind", None) in ("str",):
                continue
            var = tk.BooleanVar(value=not m.frozen)
            ttk.Checkbutton(
                self.param_frame, text=name, variable=var,
                command=lambda n=name, v=var: self._toggle_param(n, v),
            ).pack(anchor="w")
            self.param_vars[name] = var

    def _toggle_param(self, name: str, var) -> None:
        self.session.model.param_meta[name].frozen = not var.get()
        self.session.model.clear_caches()
        # status text only — no rms readout here: residuals don't depend
        # on fit flags, and the cache was just cleared (a recompute would
        # re-trace per click)
        self.status.set(f"{name} {'free' if var.get() else 'frozen'}")

    def _update_status(self, msg: str | None = None):
        s = self.session
        state = "postfit" if s.fitted else "prefit"
        # reuse the wrms the last canvas refresh computed — a status
        # update must not pay another full residual evaluation
        wrms = getattr(self.plot, "last_wrms_us", None)
        wtxt = "" if wrms is None else f", {state} wrms {wrms:.2f} us"
        base = f"{len(s.all_toas) - len(s.deleted)} TOAs{wtxt}"
        self.status.set(f"{msg}\n{base}" if msg else base)

    def refresh(self):
        mode = self.color_var.get()
        self.plot.color_flag = {"obs": "_obs", "fe-flag": "fe"}.get(mode)
        self.plot.refresh()
        self._update_status()

    # --- actions --------------------------------------------------------------

    def _set_fitter(self, value):
        self.session.fit_method = value
        self._update_status(f"fitter: {value}")

    #: sentinel distinguishing "action raised" from a legitimate None
    #: result (add_jump returns None when it removes a jump)
    _FAILED = object()

    def _guard(self, fn, label):
        try:
            return fn()
        except Exception as e:  # GUI survives bad input; log + show  # jaxlint: disable=silent-except — GUI survives bad input; error shown to the user, not a pipeline degradation
            log.warning(f"{label} failed: {e}")
            self._update_status(f"{label} failed: {e}")
            return self._FAILED

    def do_fit(self):
        res = self._guard(lambda: self.plot.fit(), "fit")
        if res is not self._FAILED:
            self._update_status(
                f"chi2 {res.chi2:.2f} / dof {res.dof}"
                f"{'' if res.converged else ' (NOT converged)'}")
            self._build_param_panel()

    def do_undo(self):
        label = self._guard(self.plot.undo, "undo")
        if label is not self._FAILED:
            self._update_status(f"undid: {label}")
            self._build_param_panel()

    def do_reset(self):
        if self._guard(self.plot.reset, "reset") is not self._FAILED:
            self._build_param_panel()
            self._update_status("reset")

    def do_clear(self):
        self.plot.clear_selection()
        self._update_status()

    def do_delete(self):
        if self._guard(self.plot.delete_selected, "delete") is not self._FAILED:
            self._update_status()

    def do_jump(self):
        name = self._guard(self.plot.jump_selected, "jump")
        if name is self._FAILED:
            return
        self._build_param_panel()
        self._update_status(f"jump: {name}" if name else "jump removed")

    def do_write_par(self):
        filedialog = self.toolkit.filedialog

        path = filedialog.asksaveasfilename(
            defaultextension=".par", initialfile=f"{self.session.name}.par")
        if path:
            self.session.write_par(path)
            self._update_status(f"wrote {path}")

    def do_write_tim(self):
        filedialog = self.toolkit.filedialog

        path = filedialog.asksaveasfilename(
            defaultextension=".tim", initialfile=f"{self.session.name}.tim")
        if path:
            self.session.write_tim(path)
            self._update_status(f"wrote {path}")

    # --- editors (reference paredit.py / timedit.py) ---------------------------

    def open_par_editor(self):
        self._open_editor(
            title="par editor",
            text=self.session.as_parfile(),
            apply=self._apply_par_text,
            save_ext=".par",
        )

    def open_tim_editor(self):
        self._open_editor(
            title="tim editor",
            text=self.session.tim_text(),
            apply=self._apply_tim_text,
            save_ext=".tim",
        )

    def _apply_par_text(self, text: str):
        self.session.apply_par_text(text)
        self.refresh()
        self._build_param_panel()
        self._update_status("applied edited par")

    def _apply_tim_text(self, text: str):
        self.session.apply_tim_text(text)
        self.refresh()
        self._update_status(
            f"loaded {len(self.session.all_toas)} TOAs from edited tim")

    def _open_editor(self, title, text, apply, save_ext):
        tk, ttk = self.toolkit.tk, self.toolkit.ttk
        filedialog = self.toolkit.filedialog

        win = tk.Toplevel(self.root)
        win.title(f"{title} — {self.session.name}")
        txt = tk.Text(win, width=90, height=40, undo=True)
        txt.insert("1.0", text)
        txt.pack(side=tk.TOP, fill=tk.BOTH, expand=True)
        bar = ttk.Frame(win)
        bar.pack(side=tk.BOTTOM, fill=tk.X)

        def do_apply():
            self._guard(lambda: apply(txt.get("1.0", "end-1c")),
                        f"{title} apply")

        def do_revert():
            txt.delete("1.0", "end")
            txt.insert("1.0", text)

        def do_save():
            path = filedialog.asksaveasfilename(defaultextension=save_ext)
            if path:
                with open(path, "w") as f:
                    f.write(txt.get("1.0", "end-1c"))

        for label, cmd in (("Apply", do_apply), ("Revert", do_revert),
                           ("Save as...", do_save), ("Close", win.destroy)):
            ttk.Button(bar, text=label, command=cmd).pack(side=tk.LEFT)
        return win

    def mainloop(self):
        self.root.mainloop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Interactive timing GUI (reference pintk)")
    ap.add_argument("parfile")
    ap.add_argument("timfile")
    ap.add_argument("--fitter", default="auto",
                    choices=PintkApp.FITTERS)
    args = ap.parse_args(argv)

    from pint_tpu.interactive import InteractivePulsar

    session = InteractivePulsar(args.parfile, args.timfile,
                                fitter=args.fitter)
    try:
        app = PintkApp(session)
    except Exception as e:  # jaxlint: disable=silent-except — GUI fit failure is reported in the status bar, not a silent fallback
        print(f"cannot open a Tk display ({e}); the matplotlib front end "
              "works headless:\n"
              "  from pint_tpu.interactive import InteractivePulsar\n"
              "  from pint_tpu.plot_utils import InteractivePlot\n"
              f"  s = InteractivePulsar({args.parfile!r}, {args.timfile!r})\n"
              "  InteractivePlot(s).connect()", file=sys.stderr)
        return 1
    app.mainloop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
