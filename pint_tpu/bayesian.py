"""Bayesian timing: jitted ln-prior / ln-likelihood / ln-posterior.

Reference: pint/bayesian.py (BayesianTiming:12 — lnprior, lnlikelihood,
lnposterior, prior_transform over the free parameters). TPU re-design:

- sampling happens in DELTA space: a walker position is an f64 offset
  vector about the model's reference parameter values, applied through
  `apply_delta` so extended-precision (dd/qf) leaves keep their low bits —
  the same mechanism the fitters use;
- the ln-posterior is ONE jitted function of the delta vector; the
  ensemble sampler vmaps it over walkers, so a whole MCMC step is a single
  compiled program (pint_tpu/sampler.py).

White-noise models use the scaled-sigma chi^2; correlated-noise models use
the Woodbury-marginalized GLS chi^2 — both reuse the fitters' machinery.
"""

from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.wls import apply_delta
from pint_tpu.priors import default_prior
from pint_tpu.residuals import Residuals, phase_residual_frac

#: memoized posterior closures: id(toas) -> {state key: (lnpost, resids)}.
#: The sampler's compiled-chain cache keys weakly on the lnpost CALLABLE
#: (pint_tpu/sampler.py _RUN_CACHE), so a resumed chain — which constructs
#: a fresh MCMCFitter/BayesianTiming, typically over a deepcopied model —
#: must get the SAME closure back or the whole chain program re-traces.
#: The key captures everything the closure's numbers depend on: component
#: skeleton, free set, precision backend, track mode, priors, and the
#: exact parameter bytes (dd low words included). TOAs is an eq-dataclass
#: (unhashable), so the outer map keys on identity with a weakref
#: finalizer evicting the entry when the TOAs object dies.
_POSTERIOR_MEMO: dict[int, dict] = {}


def _memo_for(toas) -> dict:
    ident = id(toas)
    entry = _POSTERIOR_MEMO.get(ident)
    if entry is None:
        try:
            weakref.finalize(toas, _POSTERIOR_MEMO.pop, ident, None)
        except TypeError:  # not weak-referenceable: never cached
            return {}
        entry = _POSTERIOR_MEMO[ident] = {}
    return entry


def _posterior_key(model, free, priors) -> tuple:
    comps = tuple(
        (type(c).__name__, tuple(sorted(c.specs))) for c in model.components
    )
    pbytes = tuple(
        np.asarray(leaf).tobytes()
        for leaf in jax.tree_util.tree_leaves(model.params)
    )
    priors_key = tuple((n, repr(priors[n])) for n in sorted(priors))
    return (comps, tuple(free), model.xprec.name,
            str(model.meta.get("TRACK")), pbytes, priors_key)


class BayesianTiming:
    """Posterior over the model's free parameters given prepared TOAs.

    Priors default to the reference's parfile-driven uniform windows
    (pint_tpu/priors.py); pass `priors={name: prior}` to override.

    The jitted ln-posterior closure is MEMOIZED per (toas, model state):
    two BayesianTiming instances over the same data and parameter values
    (deepcopies included) share one closure, so the sampler's compiled
    chain program is reused and a chain resume never re-traces.
    """

    def __init__(self, toas, model, priors: dict | None = None):
        self.toas = toas
        self.model = model
        self.free = tuple(model.free_params)
        self.scales = np.array(
            [model.param_meta[n].uncertainty or 1e-12 for n in self.free]
        )
        self._params0 = model.xprec.convert_params(model.params)
        self.priors = {}
        for n in self.free:
            pm = model.param_meta[n]
            v = _leaf_float(model.params[n])
            self.priors[n] = (priors or {}).get(n) or default_prior(v, pm.uncertainty)
        memo = _memo_for(toas)
        key = _posterior_key(model, self.free, self.priors)
        hit = memo.get(key)
        if hit is not None:
            self._lnpost, self.resids = hit
            return
        self.resids = Residuals(toas, model)
        self._lnpost = self._build()
        memo[key] = (self._lnpost, self.resids)

    def _build(self):
        model = self.model
        r = self.resids
        free = self.free
        params0 = self._params0
        tensor = r.tensor
        correlated = model.has_correlated_errors
        # sigma is computed IN-GRAPH from the (possibly sampled) noise
        # parameters: EFAC/EQUAD in the free set change the likelihood,
        # including its normalization
        has_noise = bool(model.noise_components)
        sigma_fixed = jnp.asarray(r.errors_s)
        n_toa = sigma_fixed.shape[0]
        track_pn, delta_pn, weights = r._track_pn, r._delta_pn, r._weights
        subtract_mean = r.subtract_mean
        prior_list = [self.priors[n] for n in free]
        v0 = jnp.asarray([_leaf_float(self.model.params[n]) for n in free])

        def lnprior(delta):
            x = v0 + delta
            lp = 0.0
            for i, pr in enumerate(prior_list):
                lp = lp + pr.logpdf(x[i])
            return lp

        def lnlike(delta):
            pp = apply_delta(params0, free, delta)
            _, rr, f = phase_residual_frac(
                model, pp, tensor,
                track_pn=track_pn, delta_pn=delta_pn,
                subtract_mean=subtract_mean, weights=weights,
            )
            rt = rr / f
            sigma = model.scaled_sigma(pp, tensor) if has_noise else sigma_fixed
            lognorm = -jnp.sum(jnp.log(sigma)) - 0.5 * n_toa * jnp.log(2 * jnp.pi)
            if not correlated:
                return -0.5 * jnp.sum((rt / sigma) ** 2) + lognorm
            # Woodbury-marginalized likelihood over the structured noise
            # basis (fitting/woodbury.py); logdet_C carries the
            # phi-dependent pieces so noise-parameter sampling stays correct
            from pint_tpu.fitting.woodbury import (
                logdet_C, s_factor, woodbury_chi2,
            )

            cinv = 1.0 / sigma**2
            basis = model.noise_basis_and_weights(pp, tensor)
            if basis is None:  # e.g. ECORR whose masks bind no epochs
                return -0.5 * jnp.sum((rt / sigma) ** 2) + lognorm
            sf = s_factor(basis, cinv)
            chi2, _ = woodbury_chi2(basis, cinv, rt, sf=sf)
            # logdet_C includes the white -sum(log w) term, replacing the
            # white branch's -sum(log sigma) half of lognorm
            return -0.5 * (
                chi2 + logdet_C(basis, cinv, sf) + n_toa * jnp.log(2 * jnp.pi)
            )

        def lnpost(delta):
            lp = lnprior(delta)
            ll = jnp.where(jnp.isfinite(lp), lnlike(delta), 0.0)
            return lp + ll

        return lnpost

    # --- public API (reference bayesian.py surface) ----------------------------

    def lnprior(self, delta: np.ndarray) -> float:
        x = np.atleast_1d(np.asarray(delta, float))
        v0 = np.array([_leaf_float(self.model.params[n]) for n in self.free])
        return float(sum(p.logpdf(v0[i] + x[i]) for i, p in enumerate([self.priors[n] for n in self.free])))

    def lnposterior(self, delta) -> float:
        return float(self._lnpost(jnp.asarray(delta)))

    @property
    def nparams(self) -> int:
        return len(self.free)

    def lnpost_fn(self):
        """The jittable delta -> ln posterior callable (for samplers)."""
        return self._lnpost


def _leaf_float(v) -> float:
    """Collapse any parameter leaf (DD, QF, plain) to a host float."""
    from pint_tpu.models.base import leaf_to_f64

    return float(np.asarray(leaf_to_f64(v)))
