"""Standalone Keplerian orbital solvers with partial derivatives.

Reference: pint/orbital/kepler.py (kepler_2d:127, inverse_kepler_2d:320,
kepler_3d:386, kepler_two_body:500) — one-object 2D/3D orbits and the full
two-body problem, each returning (state, Jacobian wrt parameters). The
reference hand-codes every chain-rule partial (~500 LoC of d_* algebra);
the TPU-first redesign writes each solver once as a pure jax function and
obtains the Jacobians by forward-mode autodiff, so state and partials come
from the same code path and cannot drift apart. The Kepler equation is the
shared differentiable fixed-iteration Newton solver
(models/binaries/kepler.py) the binary engines already use.

Units follow the reference: lengths in light-seconds, orbital periods in
DAYS, masses in solar masses, with the same gravitational constant G (in
lt-s^3 day^-2 Msun^-1 — the reference's docstrings say seconds but its G
value and its own test_mass_solar use days).
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.binaries.kepler import kepler_E

#: lt-s^3 day^-2 Msun^-1 (reference orbital/kepler.py:12, from the standard
#: gravitational parameter)
G = 36768.59290949113


def true_from_eccentric(e, eccentric_anomaly):
    """(true anomaly, d/de, d/dE) — reference true_from_eccentric:15."""
    f = lambda e, E: 2.0 * jnp.arctan2(
        jnp.sqrt(1 + e) * jnp.sin(E / 2), jnp.sqrt(1 - e) * jnp.cos(E / 2)
    )
    nu = f(e, eccentric_anomaly)
    d_de = jax.grad(f, argnums=0)(jnp.float64(e), jnp.float64(eccentric_anomaly))
    d_dE = jax.grad(f, argnums=1)(jnp.float64(e), jnp.float64(eccentric_anomaly))
    return np.float64(nu), np.float64(d_de), np.float64(d_dE)


def eccentric_from_mean(e, mean_anomaly):
    """(eccentric anomaly, [d/de, d/dM]) — reference eccentric_from_mean:45;
    the solve is the fixed-iteration Newton shared with the binary engines,
    differentiated straight through."""
    f = lambda e, M: kepler_E(M, e)
    E = f(jnp.float64(e), jnp.float64(mean_anomaly))
    d_de = jax.grad(f, argnums=0)(jnp.float64(e), jnp.float64(mean_anomaly))
    d_dM = jax.grad(f, argnums=1)(jnp.float64(e), jnp.float64(mean_anomaly))
    return np.float64(E), [np.float64(d_de), np.float64(d_dM)]


def mass(a, pb):
    """Kepler-orbit central mass [Msun] from a [lt-s], pb [days]
    (reference mass:74)."""
    return 4 * np.pi**2 * a**3 * pb ** (-2) / G


def mass_partials(a, pb):
    """(mass, [dm/da, dm/dpb]) — reference mass_partials:83."""
    m = mass(a, pb)
    return m, np.array([3 * m / a, -2 * m / pb])


def btx_parameters(asini, pb, eps1, eps2, tasc):
    """ELL1 -> BTX parameters (asini, pb, e, om, t0) —
    reference btx_parameters:93."""
    e = np.hypot(eps1, eps2)
    om = np.arctan2(eps1, eps2)
    true_anomaly = -om  # at the ascending node
    eccentric_anomaly = np.arctan2(
        np.sqrt(1 - e**2) * np.sin(true_anomaly), e + np.cos(true_anomaly)
    )
    mean_anomaly = eccentric_anomaly - e * np.sin(eccentric_anomaly)
    t0 = tasc - mean_anomaly * pb / (2 * np.pi)
    return asini, pb, e, om, t0


Kepler2DParameters = collections.namedtuple(
    "Kepler2DParameters", "a pb eps1 eps2 t0"
)
Kepler3DParameters = collections.namedtuple(
    "Kepler3DParameters", "a pb eps1 eps2 i lan t0"
)
KeplerTwoBodyParameters = collections.namedtuple(
    "KeplerTwoBodyParameters",
    "a pb eps1 eps2 i lan q x_cm y_cm z_cm vx_cm vy_cm vz_cm tasc",
)


def _kepler_2d_core(vec, t):
    """(x, y, vx, vy) of a particle on a 2D Kepler orbit; `vec` packs
    (a, pb, eps1, eps2, t0). Pure jax — the Jacobian comes from jacfwd."""
    a, pb, eps1, eps2, t0 = vec
    # autodiff-safe e/om at exact circularity: hypot/arctan2 have NaN
    # gradients at (0, 0) (the reference special-cases e == 0 in its
    # hand-written partials); the where-substitution gives e = om = 0 with
    # zero gradients there instead
    e2 = eps1**2 + eps2**2
    circ = e2 == 0.0
    e = jnp.where(circ, 0.0, jnp.sqrt(jnp.where(circ, 1.0, e2)))
    om = jnp.arctan2(jnp.where(circ, 0.0, eps1), jnp.where(circ, 1.0, eps2))
    # mean anomaly measured from the ascending node passage at t0
    nu0 = -om
    E0 = jnp.arctan2(jnp.sqrt(1 - e**2) * jnp.sin(nu0), e + jnp.cos(nu0))
    M0 = E0 - e * jnp.sin(E0)
    M = 2 * jnp.pi * (t - t0) / pb + M0
    E = kepler_E(M, e)
    cE, sE = jnp.cos(E), jnp.sin(E)
    b = a * jnp.sqrt(1 - e**2)
    # perifocal coordinates, then rotate by om
    xp = a * (cE - e)
    yp = b * sE
    Edot = (2 * jnp.pi / pb) / (1 - e * cE)
    vxp = -a * sE * Edot
    vyp = b * cE * Edot
    co, so = jnp.cos(om), jnp.sin(om)
    return jnp.array(
        [
            co * xp - so * yp,
            so * xp + co * yp,
            co * vxp - so * vyp,
            so * vxp + co * vyp,
        ]
    )


def kepler_2d(params: Kepler2DParameters, t):
    """((x, y, vx, vy), Jacobian (4, 6)) — partials wrt
    (a, pb, eps1, eps2, t0, t) (reference kepler_2d:127)."""
    vec = jnp.array([params.a, params.pb, params.eps1, params.eps2, params.t0],
                    jnp.float64)
    t = jnp.float64(t)
    xv = _kepler_2d_core(vec, t)
    jp = jax.jacfwd(_kepler_2d_core, argnums=0)(vec, t)
    jt = jax.jacfwd(_kepler_2d_core, argnums=1)(vec, t)
    return np.asarray(xv), np.concatenate(
        [np.asarray(jp), np.asarray(jt)[:, None]], axis=1
    )


def inverse_kepler_2d(xv, m, t):
    """Osculating Kepler2DParameters from a state vector
    (reference inverse_kepler_2d:320)."""
    mu = G * m
    h = xv[0] * xv[3] - xv[1] * xv[2]
    r = np.hypot(xv[0], xv[1])
    eps2, eps1 = np.array([xv[3], -xv[2]]) * h / mu - np.asarray(xv[:2]) / r
    e = np.hypot(eps1, eps2)
    p = h**2 / mu
    a = p / (1 - e**2)
    pb = 2 * np.pi * (a**3 / mu) ** 0.5
    om = np.arctan2(eps1, eps2)
    true_anomaly = np.arctan2(xv[1], xv[0]) - om
    eccentric_anomaly = np.arctan2(
        np.sqrt(1 - e**2) * np.sin(true_anomaly), e + np.cos(true_anomaly)
    )
    mean_anomaly = eccentric_anomaly - e * np.sin(eccentric_anomaly)
    nu0 = -om
    E0 = np.arctan2(np.sqrt(1 - e**2) * np.sin(nu0), e + np.cos(nu0))
    M0 = E0 - e * np.sin(E0)
    return Kepler2DParameters(
        a=a, pb=pb, eps1=eps1, eps2=eps2,
        t0=t - (mean_anomaly - M0) * pb / (2 * np.pi),
    )


def _kepler_3d_core(vec, t):
    """(x, y, z, vx, vy, vz): the 2D orbit rotated by inclination about x,
    then by the longitude of ascending node about z."""
    a, pb, eps1, eps2, inc, lan, t0 = vec
    xv2 = _kepler_2d_core(jnp.array([a, pb, eps1, eps2, t0]), t)
    pos = jnp.array([xv2[0], xv2[1], 0.0])
    vel = jnp.array([xv2[2], xv2[3], 0.0])
    ci, si = jnp.cos(inc), jnp.sin(inc)
    r_i = jnp.array([[1.0, 0.0, 0.0], [0.0, ci, -si], [0.0, si, ci]])
    # reference convention (kepler_3d:420): rotation by -lan about z
    cl, sl = jnp.cos(lan), jnp.sin(lan)
    r_l = jnp.array([[cl, sl, 0.0], [-sl, cl, 0.0], [0.0, 0.0, 1.0]])
    R = r_l @ r_i
    return jnp.concatenate([R @ pos, R @ vel])


def kepler_3d(params: Kepler3DParameters, t):
    """((x, y, z, vx, vy, vz), Jacobian (6, 8)) — partials wrt
    (a, pb, eps1, eps2, i, lan, t0, t) (reference kepler_3d:386)."""
    vec = jnp.array(
        [params.a, params.pb, params.eps1, params.eps2, params.i,
         params.lan, params.t0], jnp.float64,
    )
    t = jnp.float64(t)
    xv = _kepler_3d_core(vec, t)
    jp = jax.jacfwd(_kepler_3d_core, argnums=0)(vec, t)
    jt = jax.jacfwd(_kepler_3d_core, argnums=1)(vec, t)
    return np.asarray(xv), np.concatenate(
        [np.asarray(jp), np.asarray(jt)[:, None]], axis=1
    )


def inverse_kepler_3d(xyv, m, t):
    """Osculating Kepler3DParameters from a 3D state
    (reference inverse_kepler_3d)."""
    xyv = np.asarray(xyv, float)
    L = np.cross(xyv[:3], xyv[3:])
    inc = np.arccos(L[2] / np.linalg.norm(L))
    lan = (-np.arctan2(L[0], -L[1])) % (2 * np.pi)
    cl, sl = np.cos(lan), np.sin(lan)
    r_l = np.array([[cl, sl, 0.0], [-sl, cl, 0.0], [0.0, 0.0, 1.0]])
    ci, si = np.cos(inc), np.sin(inc)
    r_i = np.array([[1.0, 0.0, 0.0], [0.0, ci, -si], [0.0, si, ci]])
    R = (r_l @ r_i).T
    pos = R @ xyv[:3]
    vel = R @ xyv[3:]
    p2 = inverse_kepler_2d(np.array([pos[0], pos[1], vel[0], vel[1]]), m, t)
    return Kepler3DParameters(
        a=p2.a, pb=p2.pb, eps1=p2.eps1, eps2=p2.eps2, i=inc, lan=lan, t0=p2.t0
    )


def _two_body_core(vec, t):
    """Reference total_state layout (kepler_two_body:572-582):
    [x_p, v_p, m_p, x_c, v_c, m_c] (14 entries); `vec` packs the
    KeplerTwoBodyParameters fields. The center of mass is displaced by
    (x_cm, v_cm) as constant offsets, exactly like the reference."""
    a, pb, eps1, eps2, inc, lan, q = vec[:7]
    x_cm = vec[7:10]
    v_cm = vec[10:13]
    tasc = vec[13]
    a_tot = a * (1 + 1.0 / q)
    m_tot = 4 * jnp.pi**2 * a_tot**3 / (pb**2 * G)
    m = m_tot / (1 + q)
    m_c = q * m
    xv_tot = _kepler_3d_core(jnp.array([a_tot, pb, eps1, eps2, inc, lan, tasc]), t)
    xv = xv_tot / (1 + 1.0 / q)
    xv_c = -xv / q
    prim = jnp.concatenate([xv[:3] + x_cm, xv[3:] + v_cm])
    comp = jnp.concatenate([xv_c[:3] + x_cm, xv_c[3:] + v_cm])
    return jnp.concatenate([prim, jnp.array([m]), comp, jnp.array([m_c])])


def kepler_two_body(params: KeplerTwoBodyParameters, t):
    """(total_state, Jacobian (14, 15)) — total_state is the reference's
    [x_p, v_p, m_p, x_c, v_c, m_c] layout; partials wrt the 14 parameters
    then t (reference kepler_two_body:500). The primary's orbit has
    semi-major axis `a`; the companion's mass is q x the primary's."""
    vec = jnp.array(
        [params.a, params.pb, params.eps1, params.eps2, params.i, params.lan,
         params.q, params.x_cm, params.y_cm, params.z_cm, params.vx_cm,
         params.vy_cm, params.vz_cm, params.tasc], jnp.float64,
    )
    t = jnp.float64(t)
    out = _two_body_core(vec, t)
    jp = jax.jacfwd(_two_body_core, argnums=0)(vec, t)
    jt = jax.jacfwd(_two_body_core, argnums=1)(vec, t)
    return np.asarray(out), np.concatenate(
        [np.asarray(jp), np.asarray(jt)[:, None]], axis=1
    )


def inverse_kepler_two_body(total_state, t):
    """Recover KeplerTwoBodyParameters from the two bodies' states + masses
    (reference inverse_kepler_two_body:586)."""
    out = np.asarray(total_state, float)
    xv_p, m, xv_c, m_c = out[:6], out[6], out[7:13], out[13]
    q = m_c / m
    x_cm = (m * xv_p[:3] + m_c * xv_c[:3]) / (m + m_c)
    v_cm = (m * xv_p[3:] + m_c * xv_c[3:]) / (m + m_c)
    rel = np.concatenate([xv_p[:3] - xv_c[:3], xv_p[3:] - xv_c[3:]])
    p3 = inverse_kepler_3d(rel, m + m_c, t)
    a = p3.a / (1 + 1.0 / q)
    return KeplerTwoBodyParameters(
        a=a, pb=p3.pb, eps1=p3.eps1, eps2=p3.eps2, i=p3.i, lan=p3.lan, q=q,
        x_cm=x_cm[0], y_cm=x_cm[1], z_cm=x_cm[2],
        vx_cm=v_cm[0], vy_cm=v_cm[1], vz_cm=v_cm[2], tasc=p3.t0,
    )
