"""Device-resident MCMC kernels: stretch ensembles + HMC chains.

Reference: pint/sampler.py (EmceeSampler:60 wrapping emcee) and
mcmc_fitter.py. TPU re-design: the Goodman & Weare (2010) stretch move is
implemented directly in JAX — walkers are a vmapped batch axis of the
jitted ln-posterior, the two half-ensembles update alternately (the
standard parallel variant, Foreman-Mackey et al. 2013 §3), and the whole
chain is ONE `lax.scan` compiled program. Fixed-seed deterministic
(SURVEY §4.6).

Two composable chain BUILDERS serve the noise engine
(fitting/noise_like.py) and any other posterior:

- `make_stretch_chain(lnpost, nsteps)`: the ensemble move as a
  scan-kernel over (walkers, ndim) state, with arbitrary trailing
  context operands threaded to the posterior;
- `make_hmc_chain(lnpost, nsteps, warmup, ...)`: Hamiltonian Monte Carlo
  with dual-averaging step-size warmup (Hoffman & Gelman 2014, Alg. 5 —
  the NUTS adaptation recipe on a fixed-length leapfrog trajectory) as
  ONE `lax.scan`. Divergent trajectories (non-finite or exploding
  energy) are rejected by `where` masks — under `jax.vmap` each chain
  masks its own divergences, so C chains advance in lockstep as one
  executable with per-chain trajectories identical to solo runs.

Both kernels take `lnpost(x, *ctx)`; vmapping over chains/pulsars is the
caller's composition (noise_like.NoiseLikelihood.sample / NoiseFleet).
"""

from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray


# --- chain builders ---------------------------------------------------------------


def make_stretch_chain(lnpost, nsteps: int, a: float = 2.0):
    """Build the stretch-ensemble chain kernel.

    Returns ``chain(x0 (W, nd), key, *ctx) -> {"samples": (S, W, nd),
    "lnpost": (S, W), "accept": (S,)}`` — the whole chain one lax.scan.
    """

    def chain(x0, key, *ctx):
        vln = jax.vmap(lambda x: lnpost(x, *ctx))

        def half_step(key, x_move, lp_move, x_other):
            half, nd = x_move.shape
            k1, k2, k3 = jax.random.split(key, 3)
            u = jax.random.uniform(k1, (half,))
            z = ((a - 1.0) * u + 1.0) ** 2 / a
            partners = jax.random.randint(k2, (half,), 0, half)
            xp = x_other[partners]
            prop = xp + z[:, None] * (x_move - xp)
            lp_prop = vln(prop)
            ln_accept = (nd - 1) * jnp.log(z) + lp_prop - lp_move
            accept = jnp.log(jax.random.uniform(k3, (half,))) < ln_accept
            x_new = jnp.where(accept[:, None], prop, x_move)
            lp_new = jnp.where(accept, lp_prop, lp_move)
            return x_new, lp_new, accept

        def step(carry, key):
            x, lp = carry
            half = x.shape[0] // 2
            ka, kb = jax.random.split(key)
            xa, lpa, acc_a = half_step(ka, x[:half], lp[:half], x[half:])
            xb, lpb, acc_b = half_step(kb, x[half:], lp[half:], xa)
            x = jnp.concatenate([xa, xb])
            lp = jnp.concatenate([lpa, lpb])
            n_acc = jnp.sum(acc_a) + jnp.sum(acc_b)
            return (x, lp), (x, lp, n_acc)

        lp0 = vln(x0)
        keys = jax.random.split(key, nsteps)
        (_, _), (xs, lps, n_acc) = jax.lax.scan(step, (x0, lp0), keys)
        return {
            "samples": xs,
            "lnpost": lps,
            "accept": n_acc / x0.shape[0],
        }

    return chain


def make_hmc_chain(lnpost, nsteps: int, warmup: int,
                   target_accept: float = 0.8, max_leapfrog: int = 8,
                   step_size0: float = 0.1,
                   divergence_energy: float = 1000.0):
    """Build the HMC chain kernel with dual-averaging warmup.

    Returns ``chain(x0 (nd,), key, *ctx) -> {"samples": (S, nd),
    "lnpost": (S,), "accept": (S,), "divergent": (S,), "step_size": ()}``
    where S counts POST-warmup draws only; the whole (warmup + sampling)
    trajectory is one lax.scan. The caller is expected to run in
    unit-scaled coordinates (identity mass matrix) — noise_like wraps the
    posterior in prior-scaled space for exactly that reason.

    Dual averaging (Hoffman & Gelman 2014, Alg. 5): during warmup the log
    step size tracks the target acceptance statistic; after warmup the
    averaged iterate is frozen. A proposal whose energy error is
    non-finite or exceeds `divergence_energy` is DIVERGENT: rejected
    outright (masked per chain under vmap) and counted.
    """
    gamma, t0, kappa = 0.05, 10.0, 0.75
    mu = float(np.log(10.0 * step_size0))
    vg = jax.value_and_grad(lnpost, argnums=0)

    def chain(x0, key, *ctx):
        def vg_safe(x):
            lp, g = vg(x, *ctx)
            return lp, jnp.where(jnp.isfinite(g), g, 0.0)

        lp0, g0 = vg_safe(x0)

        def leapfrog(x, g, p, eps):
            def lf_step(carry, _):
                x, g, p = carry
                p = p + 0.5 * eps * g
                x = x + eps * p
                lp, g = vg_safe(x)
                p = p + 0.5 * eps * g
                return (x, g, p), lp

            (x, g, p), lps = jax.lax.scan(
                lf_step, (x, g, p), None, length=max_leapfrog)
            return x, g, p, lps[-1]

        def step(carry, inp):
            x, lp, g, log_eps, log_eps_bar, h_bar = carry
            m, key = inp
            k1, k2 = jax.random.split(key)
            in_warmup = m < warmup
            eps = jnp.exp(jnp.where(in_warmup, log_eps, log_eps_bar))
            p0 = jax.random.normal(k1, x.shape)
            h0 = -lp + 0.5 * jnp.sum(p0 * p0)
            x1, g1, p1, lp1 = leapfrog(x, g, p0, eps)
            h1 = -lp1 + 0.5 * jnp.sum(p1 * p1)
            d_h = h0 - h1  # > 0 favors acceptance
            # divergent = the PROPOSAL's energy exploded (NaN, or energy
            # error past the threshold). d_h = +inf — escaping a start
            # outside the prior support — is a certain accept, not a
            # divergence, or chains initialized at lnpost = -inf would
            # mask-reject every move forever.
            divergent = jnp.isnan(d_h) | (d_h < -divergence_energy)
            alpha = jnp.where(divergent, 0.0,
                              jnp.minimum(1.0, jnp.exp(jnp.minimum(d_h, 0.0))))
            accept = (~divergent) & (
                jnp.log(jax.random.uniform(k2, ())) < d_h)
            x = jnp.where(accept, x1, x)
            lp = jnp.where(accept, lp1, lp)
            g = jnp.where(accept, g1, g)
            # dual averaging (warmup only; frozen after)
            mw = jnp.minimum(m, warmup - 1) + 1.0  # 1-based warmup index
            eta_h = 1.0 / (mw + t0)
            h_new = (1.0 - eta_h) * h_bar + eta_h * (target_accept - alpha)
            le_new = mu - jnp.sqrt(mw) / gamma * h_new
            eta_x = mw ** (-kappa)
            leb_new = eta_x * le_new + (1.0 - eta_x) * log_eps_bar
            log_eps = jnp.where(in_warmup, le_new, log_eps)
            log_eps_bar = jnp.where(in_warmup, leb_new, log_eps_bar)
            h_bar = jnp.where(in_warmup, h_new, h_bar)
            carry = (x, lp, g, log_eps, log_eps_bar, h_bar)
            return carry, (x, lp, accept, divergent)

        total = warmup + nsteps
        keys = jax.random.split(key, total)
        ms = jnp.arange(total, dtype=jnp.float64)
        init = (x0, lp0, g0,
                jnp.asarray(np.log(step_size0), jnp.float64),
                jnp.asarray(np.log(step_size0), jnp.float64),
                jnp.asarray(0.0, jnp.float64))
        carry, (xs, lps, acc, div) = jax.lax.scan(step, init, (ms, keys))
        return {
            "samples": xs[warmup:],
            "lnpost": lps[warmup:],
            "accept": acc[warmup:],
            "divergent": div[warmup:],
            "step_size": jnp.exp(carry[4]),
        }

    return chain


def make_scaled_chain(make_kernel, lnpost):
    """Laplace-scaled-coordinate wrapper shared by the noise engine and
    the joint PTA likelihood: returns ``chain(z0, key, center, scales,
    *ctx)`` running ``make_kernel(lnpost_z)`` in centered, scaled
    coordinates z = (x - center) / scales — the diagonal mass matrix HMC
    assumes — with draws mapped back to x on device. ``center``/``scales``
    are ARGUMENTS (not closure), so a fleet vmaps per-member values
    through one compiled program."""

    def chain(z0, key, center, scales, *ctx):
        def lnpost_z(z, *c):
            return lnpost(center + z * scales, *c)

        out = make_kernel(lnpost_z)(z0, key, *ctx)
        out["samples"] = center + out["samples"] * scales
        return out

    return chain


# --- the classic walker-ensemble surface ------------------------------------------

#: compiled chain programs keyed on the lnpost CALLABLE (weakly, so dead
#: posteriors — which capture whole datasets — are not pinned): re-running
#: a fitter or resuming a chain must NOT re-trace, because the sampler
#: graph embeds the whole posterior and rebuilding it costs far more than
#: the sampling. Producers must hand back the SAME closure across calls
#: (BayesianTiming memoizes its posterior per (toas, model-state) so a
#: resumed MCMCFitter — even over a deepcopied model — reuses the
#: compiled chain; EventOptimizer memoizes too.)
_RUN_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _get_run(lnpost, a: float):
    per_a = _RUN_CACHE.get(lnpost)
    if per_a is not None and a in per_a:
        return per_a[a]

    from pint_tpu.ops.compile import TimedProgram, precision_jit

    def run(x0, key, nsteps: int):
        return make_stretch_chain(lnpost, nsteps, a)(x0, key)

    # static nsteps: a longer resume segment is a new program (same as the
    # old split-key signature); the TimedProgram wrapper makes compiles
    # visible to the perf breakdown and the jaxpr auditor
    # the chain state is a plain f64 hyperparameter vector; the posterior's
    # internal dd arithmetic closes over the model (spec mode "f64")
    prog = TimedProgram(precision_jit(run, static_argnums=(2,)), "mcmc_chain",
                        precision_spec="f64")
    _RUN_CACHE.setdefault(lnpost, {})[a] = prog
    return prog


def run_ensemble(lnpost, x0: np.ndarray, nsteps: int, seed: int = 0, a: float = 2.0):
    """Run the stretch sampler.

    lnpost : delta-vector -> scalar ln posterior (jit-traceable; reuse the
        SAME callable across calls to reuse the compiled chain)
    x0 : (nwalkers, ndim) initial walker positions (nwalkers even)
    Returns (chain (nsteps, nwalkers, ndim), lnp (nsteps, nwalkers),
    acceptance fraction).
    """
    x0 = jnp.asarray(x0, jnp.float64)
    nw, nd = x0.shape
    if nw % 2 or nw < 2 * nd:
        raise ValueError(f"need an even nwalkers >= 2*ndim, got {nw} for ndim {nd}")
    run = _get_run(lnpost, a)
    out = run(x0, jax.random.PRNGKey(seed), nsteps)
    accept_frac = float(jnp.mean(out["accept"]))
    return np.asarray(out["samples"]), np.asarray(out["lnpost"]), accept_frac


def initial_ball(scales: np.ndarray, nwalkers: int, seed: int = 0,
                 spread: float = 0.1) -> np.ndarray:
    """Walkers in a Gaussian ball of `spread` parameter-uncertainties
    around zero delta (reference MCMCFitter initial positions)."""
    rng = np.random.default_rng(seed)
    return rng.standard_normal((nwalkers, len(scales))) * scales * spread
