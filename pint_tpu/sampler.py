"""Ensemble MCMC: affine-invariant stretch sampler, fully jitted.

Reference: pint/sampler.py (EmceeSampler:60 wrapping emcee) and
mcmc_fitter.py. TPU re-design: the Goodman & Weare (2010) stretch move is
implemented directly in JAX — walkers are a vmapped batch axis of the
jitted ln-posterior, the two half-ensembles update alternately (the
standard parallel variant, Foreman-Mackey et al. 2013 §3), and the whole
chain is ONE `lax.scan` compiled program. Fixed-seed deterministic
(SURVEY §4.6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray


#: compiled chain programs keyed on the lnpost CALLABLE (weakly, so dead
#: posteriors — which capture whole datasets — are not pinned): re-running
#: a fitter or resuming a chain must NOT re-trace, because the sampler
#: graph embeds the whole posterior and rebuilding it costs far more than
#: the sampling. Producers must hand back the SAME closure across calls
#: (BayesianTiming/EventOptimizer memoize theirs).
import weakref

_RUN_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _get_run(lnpost, a: float):
    per_a = _RUN_CACHE.get(lnpost)
    if per_a is not None and a in per_a:
        return per_a[a]

    vln = jax.vmap(lnpost)

    def half_step(key, x_move, lp_move, x_other):
        half, nd = x_move.shape
        k1, k2, k3 = jax.random.split(key, 3)
        u = jax.random.uniform(k1, (half,))
        z = ((a - 1.0) * u + 1.0) ** 2 / a
        partners = jax.random.randint(k2, (half,), 0, half)
        xp = x_other[partners]
        prop = xp + z[:, None] * (x_move - xp)
        lp_prop = vln(prop)
        ln_accept = (nd - 1) * jnp.log(z) + lp_prop - lp_move
        accept = jnp.log(jax.random.uniform(k3, (half,))) < ln_accept
        x_new = jnp.where(accept[:, None], prop, x_move)
        lp_new = jnp.where(accept, lp_prop, lp_move)
        return x_new, lp_new, accept

    def step(carry, key):
        x, lp = carry
        half = x.shape[0] // 2
        ka, kb = jax.random.split(key)
        xa, lpa, acc_a = half_step(ka, x[:half], lp[:half], x[half:])
        xb, lpb, acc_b = half_step(kb, x[half:], lp[half:], xa)
        x = jnp.concatenate([xa, xb])
        lp = jnp.concatenate([lpa, lpb])
        n_acc = jnp.sum(acc_a) + jnp.sum(acc_b)
        return (x, lp), (x, lp, n_acc)

    @jax.jit
    def run(x0, keys):
        lp0 = vln(x0)
        (_, _), (chain, lnp, n_acc) = jax.lax.scan(step, (x0, lp0), keys)
        return chain, lnp, n_acc

    _RUN_CACHE.setdefault(lnpost, {})[a] = run
    return run


def run_ensemble(lnpost, x0: np.ndarray, nsteps: int, seed: int = 0, a: float = 2.0):
    """Run the stretch sampler.

    lnpost : delta-vector -> scalar ln posterior (jit-traceable; reuse the
        SAME callable across calls to reuse the compiled chain)
    x0 : (nwalkers, ndim) initial walker positions (nwalkers even)
    Returns (chain (nsteps, nwalkers, ndim), lnp (nsteps, nwalkers),
    acceptance fraction).
    """
    x0 = jnp.asarray(x0, jnp.float64)
    nw, nd = x0.shape
    if nw % 2 or nw < 2 * nd:
        raise ValueError(f"need an even nwalkers >= 2*ndim, got {nw} for ndim {nd}")
    run = _get_run(lnpost, a)
    keys = jax.random.split(jax.random.PRNGKey(seed), nsteps)
    chain, lnp, n_acc = run(x0, keys)
    accept_frac = float(jnp.sum(n_acc)) / (nsteps * nw)
    return np.asarray(chain), np.asarray(lnp), accept_frac


def initial_ball(scales: np.ndarray, nwalkers: int, seed: int = 0,
                 spread: float = 0.1) -> np.ndarray:
    """Walkers in a Gaussian ball of `spread` parameter-uncertainties
    around zero delta (reference MCMCFitter initial positions)."""
    rng = np.random.default_rng(seed)
    return rng.standard_normal((nwalkers, len(scales))) * scales * spread
