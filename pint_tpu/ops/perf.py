"""Stage-level performance telemetry for the fit path.

The flagship bench showed a 91 s first `fit_toas()` on the 100k-TOA set
while timing it as one opaque block (BENCH_r05 "initial_fit_s"), so nobody
could say whether compile, device steps, host solves, or transfers were to
blame. This module is the measuring instrument: a nesting stage timer plus
counters that the fitters (fitting/wls.py, gls.py, wideband.py) and the
compile layer (ops/compile.py) report into, aggregated into a per-fit
breakdown (`fit_breakdown`) that lands on ``FitResult.perf`` and in the
bench headline record.

Design constraints:

- **Near-zero cost when off.** Nothing is recorded unless a report is
  active; `stage()` then returns one shared no-op context manager and
  `add`/`put` are a single empty-list check. The fit path stays exactly
  as fast as before when telemetry is off.
- **Thread-aware.** The report registry is process-global (so the
  overlapped precompile worker threads report into the same collection),
  while the stage-nesting *path* is thread-local (so a worker's stages
  don't splice into the fit thread's nesting).
- **Nesting aggregates by path.** ``stage("fit")`` containing
  ``stage("step")`` records under ``"fit"`` and ``"fit/step"``; repeated
  entries of the same path sum their durations and count entries, so
  per-iteration means fall out of (total, count).

Enable with ``PINT_TPU_PERF=1`` (every fit then attaches a breakdown), or
programmatically::

    from pint_tpu.ops import perf
    with perf.collect() as report:
        fitter.fit_toas()
    print(report.summary())          # raw stage/counter dump
    print(fitter.result.perf)        # canonical fit breakdown
"""

from __future__ import annotations

import functools
import math
import threading
import time
from contextlib import contextmanager

from pint_tpu.utils import knobs

__all__ = [
    "INCR_COUNTERS", "PerfReport", "QuantileSketch", "SERVE_COUNTERS",
    "active", "add", "campaign_breakdown", "collect", "enable",
    "enabled", "fit_breakdown", "incremental_breakdown",
    "instrument_fit", "noise_breakdown", "prepare_breakdown",
    "pta_breakdown", "put", "put_default", "serve_breakdown",
    "set_metrics_feed", "stage",
]

_env_enabled = knobs.flag("PINT_TPU_PERF")
# all reports currently collecting; stage/add/put record into every one
_reports: list["PerfReport"] = []
_tls = threading.local()  # .path: list[str] — per-thread stage nesting
# guards every report mutation (timings/counters/values): the serving
# engine's worker, watchdog and client threads record concurrently, and
# an unlocked read-modify-write on a counter LOSES bumps under the GIL's
# preemption (locked by the tests/test_serve.py ledger hammer)
_rec_lock = threading.Lock()


class PerfReport:
    """Aggregated stage timings + counters + latched values."""

    def __init__(self):
        # path -> [total_seconds, count]
        self.timings: dict[str, list] = {}
        # name -> accumulated value
        self.counters: dict[str, float] = {}
        # name -> last latched value (solve_path, latch reason, ...)
        self.values: dict[str, object] = {}

    def seconds(self, path: str) -> float:
        t = self.timings.get(path)
        return 0.0 if t is None else t[0]

    def count(self, path: str) -> int:
        t = self.timings.get(path)
        return 0 if t is None else int(t[1])

    def summary(self) -> dict:
        """JSON-ready dump of everything recorded."""
        return {
            "timings_s": {
                p: {"total": round(t[0], 6), "count": int(t[1])}
                for p, t in sorted(self.timings.items())
            },
            "counters": dict(self.counters),
            "values": dict(self.values),
        }


def enable(flag: bool = True) -> None:
    """Process-wide default: every subsequent fit collects its own report
    (equivalent to PINT_TPU_PERF=1)."""
    global _env_enabled
    _env_enabled = flag


def enabled() -> bool:
    """True when fits should collect telemetry (env/programmatic flag, or
    a `collect()` scope is already open)."""
    return _env_enabled or bool(_reports)


def active() -> bool:
    """True when at least one report is collecting right now."""
    return bool(_reports)


@contextmanager
def collect():
    """Open a collection scope: stages/counters inside record into the
    yielded report (in every thread). Scopes nest — an inner `collect`
    (e.g. a fit's own breakdown) records into the outer report too."""
    rep = PerfReport()
    _reports.append(rep)
    try:
        yield rep
    finally:
        _reports.remove(rep)


class _NullStage:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullStage()


class _Stage:
    __slots__ = ("name", "_t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        path = getattr(_tls, "path", None)
        if path is None:
            path = _tls.path = []
        path.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        path = _tls.path
        key = "/".join(path)
        path.pop()
        if not _reports:
            return False
        with _rec_lock:
            for rep in _reports:
                t = rep.timings.get(key)
                if t is None:
                    rep.timings[key] = [dt, 1]
                else:
                    t[0] += dt
                    t[1] += 1
        return False


def stage(name: str):
    """Timed, nestable stage. No-op (shared null object) when nothing is
    collecting."""
    if not _reports:
        return _NULL
    return _Stage(name)


#: the metrics-export forwarding hook (pint_tpu/obs/metrics.py installs
#: it on first registry use): every counter bump is offered to the
#: process-global metrics registry, which exports the registered subset
#: — the existing telemetry stays the single measurement point. None
#: (the default) costs one identity check per add().
_metrics_feed = None


def set_metrics_feed(fn) -> None:
    """Install (or remove, fn=None) the counter-export hook."""
    global _metrics_feed
    _metrics_feed = fn


def add(name: str, value: float = 1.0) -> None:
    """Accumulate a counter (transfers, bytes, trials, ...). Thread-safe:
    concurrent bumps from serving worker + client threads never lose a
    count (the lock is skipped entirely when nothing is collecting).
    With the metrics feed installed, every bump is ALSO offered to the
    process-global export registry — counters export even when no perf
    report is collecting (a production process scrapes /metrics without
    paying for per-fit breakdowns)."""
    if _metrics_feed is not None:
        _metrics_feed(name, value)
    if not _reports:
        return
    with _rec_lock:
        for rep in _reports:
            rep.counters[name] = rep.counters.get(name, 0) + value


def put(name: str, value) -> None:
    """Latch a value (e.g. solve_path); last write wins."""
    if not _reports:
        return
    with _rec_lock:
        for rep in _reports:
            rep.values[name] = value


def put_default(name: str, value) -> None:
    """Latch a value only where nothing latched it yet."""
    if not _reports:
        return
    with _rec_lock:
        for rep in _reports:
            rep.values.setdefault(name, value)


# --- the canonical prepare breakdown ---------------------------------------------

#: prepare sub-stages named in the breakdown; anything else directly under
#: a "prepare" stage lands in prepare_other_s. These are the host (or
#: device-program) steps of the TOA-prepare pipeline: the clock chain,
#: EOP lookup, site geometry, ephemeris evaluation, time-scale
#: conversion, the TZR fiducial prepare, longdouble->dd64 conversion,
#: model-column assembly and the host->device transfers.
_PREPARE_COMPONENTS = (
    "clock", "eop", "geometry", "ephemeris", "tdb", "tzr",
    "dd_convert", "columns", "transfer", "cache",
)


def prepare_breakdown(rep: PerfReport) -> dict:
    """Map "prepare"-rooted stages into the canonical prepare breakdown.

    Prepare stages nest anywhere (a bare `prepare_arrays` call, the TZR
    prepare inside `build_tensor`'s own prepare stage, a prepare inside an
    instrumented fit): a path contributes to the wall when its FIRST
    ``prepare`` segment is its leaf, and to a component when the segment
    after that first ``prepare`` is its leaf — deeper nestings (e.g. the
    TZR row's own ``.../tzr/prepare/clock``) are already inside their
    parent component, so the named fields partition the prepare wall.
    """
    wall = 0.0
    comp = {leaf: 0.0 for leaf in _PREPARE_COMPONENTS}
    direct = 0.0
    kernel_build = 0.0
    kb_in_ephemeris = 0.0
    for path, (total, _count) in rep.timings.items():
        segs = path.split("/")
        # kernel-pack builds (astro/kernel_ephemeris.cached_pack) nest
        # inside whatever serve triggered them — name them wherever they
        # are so the pack-build cost is attributable on its own
        if segs[-1] == "kernel_build":
            kernel_build += total
            if "ephemeris" in segs:
                kb_in_ephemeris += total
        if "prepare" not in segs:
            continue
        i = segs.index("prepare")
        if len(segs) == i + 1:
            wall += total
        elif len(segs) == i + 2:
            direct += total
            if segs[-1] in comp:
                comp[segs[-1]] += total
    out = {"prepare_wall_s": round(wall, 4)}
    for leaf in _PREPARE_COMPONENTS:
        out[f"prepare_{leaf}_s"] = round(comp[leaf], 4)
    out["prepare_other_s"] = round(max(wall - direct, 0.0), 4)
    out["prepare_cache_hits"] = int(rep.counters.get("prepare_cache_hits", 0))
    out["prepare_cache_misses"] = int(
        rep.counters.get("prepare_cache_misses", 0))
    out["nbody_window_builds"] = int(
        rep.counters.get("nbody_window_builds", 0))
    out["nbody_cache_hits"] = int(rep.counters.get("nbody_cache_hits", 0))
    out["nbody_cache_misses"] = int(
        rep.counters.get("nbody_cache_misses", 0))
    out["prepare_device_programs"] = int(
        rep.counters.get("prepare_device_programs", 0))
    # kernel-pack telemetry (astro/kernel_ephemeris.py): build wall,
    # cache traffic, and the per-TOA ephemeris serve cost with the
    # one-time pack build excluded (the number a capacity plan needs)
    out["prepare_kernel_build_s"] = round(kernel_build, 4)
    out["kernel_pack_cache_hits"] = int(
        rep.counters.get("kernel_pack_cache_hits", 0))
    out["kernel_pack_cache_misses"] = int(
        rep.counters.get("kernel_pack_cache_misses", 0))
    serve_toas = rep.counters.get("ephemeris_serve_toas", 0)
    serve_s = max(comp["ephemeris"] - kb_in_ephemeris, 0.0)
    out["ephemeris_serve_us_per_toa"] = (
        round(serve_s / serve_toas * 1e6, 3) if serve_toas else None)
    return out


# --- the canonical noise-analysis breakdown --------------------------------------

#: noise sub-stages named in the breakdown (fitting/noise_like.py): basis
#: construction + (r0, M) linearization (`build`), batched likelihood/
#: gradient evaluations (`eval`), vmapped chain programs (`chain`) and
#: the batched optimizer restarts (`optimize`); anything else directly
#: under a `noise` stage lands in noise_other_s.
_NOISE_COMPONENTS = ("build", "eval", "chain", "optimize")


def _root_breakdown(rep: PerfReport, root: str,
                    components: tuple[str, ...]) -> dict:
    """Map `root`-rooted stages into a canonical breakdown: named
    components + compile + trace + other partition the `root` wall
    (compile/trace nests inside the component that triggered it and is
    subtracted there). Shared by the noise and PTA engines."""
    wall = 0.0
    comp = {leaf: 0.0 for leaf in components}
    nested_ct = {leaf: 0.0 for leaf in components}
    compile_s = trace_s = 0.0
    direct = 0.0
    for path, (total, _count) in rep.timings.items():
        segs = path.split("/")
        if root not in segs:
            continue
        i = segs.index(root)
        if len(segs) == i + 1:
            wall += total
        elif len(segs) == i + 2:
            direct += total
            if segs[-1] in comp:
                comp[segs[-1]] += total
        if segs[-1] in ("compile", "trace") and len(segs) > i + 1:
            if segs[-1] == "compile":
                compile_s += total
            else:
                trace_s += total
            if len(segs) > i + 2 and segs[i + 1] in nested_ct:
                nested_ct[segs[i + 1]] += total
    out = {f"{root}_wall_s": round(wall, 4)}
    for leaf in components:
        out[f"{root}_{leaf}_s"] = round(comp[leaf] - nested_ct[leaf], 4)
    out[f"{root}_compile_s"] = round(compile_s, 4)
    out[f"{root}_trace_s"] = round(trace_s, 4)
    out[f"{root}_other_s"] = round(max(wall - direct, 0.0), 4)
    return out


def noise_breakdown(rep: PerfReport) -> dict:
    """Map "noise"-rooted stages into the canonical noise breakdown.

    The contract (enforced by the --smoke --noise bench, tests/
    test_noise_like.py): named components + compile + trace + other
    account for the noise wall, so the Bayesian-engine telemetry cannot
    silently rot. Counters: `noise_loglike_evals` is every marginalized
    likelihood (or gradient) evaluation served, `noise_chain_steps` is
    chain-step draws (walker-steps for the stretch kernel),
    `noise_divergences` counts masked divergent HMC trajectories,
    `fleet_stack_reuse` counts bucket-padded member layouts served from
    the per-member memo instead of re-padded (NoiseFleet /
    PTALikelihood construction over a resident member set), and
    `stack_slot_reuse` counts stacked slots whose device buffers were
    reused across a rebuild (fitting/batch.py placed_stack — the
    per-slot invalidation contract).
    """
    out = _root_breakdown(rep, "noise", _NOISE_COMPONENTS)
    out["noise_loglike_evals"] = int(rep.counters.get("noise_loglike_evals", 0))
    out["noise_chain_steps"] = int(rep.counters.get("noise_chain_steps", 0))
    out["noise_divergences"] = int(rep.counters.get("noise_divergences", 0))
    out["fleet_stack_reuse"] = int(rep.counters.get("fleet_stack_reuse", 0))
    out["stack_slot_reuse"] = int(rep.counters.get("stack_slot_reuse", 0))
    return out


# --- the canonical joint-PTA breakdown -------------------------------------------

#: PTA sub-stages named in the breakdown (fitting/pta_like.py): ORF/span
#: assembly + joint-program setup + Laplace scales (`build`), per-member
#: bucket-padded layout + host slot stacking (`stack`), device placement
#: of the stacked operands by mesh coordinate (`place`), fused joint
#: likelihood/gradient evaluations (`eval`), vmapped joint chains
#: (`chain`) and batched optimizer restarts (`optimize`); anything else
#: directly under a `pta` stage lands in pta_other_s. The in-graph psum
#: and replicated dense-solve halves of an eval cannot be host-timed
#: (they live inside ONE fused program), so the breakdown carries their
#: STATIC shape instead: `pta_psum_bytes_per_eval` (the interconnect
#: payload of the one completing psum) and `pta_solve_dim` (the
#: replicated Sigma+timing solve dimension N·m + N·p), latched at
#: program-build time.
_PTA_COMPONENTS = ("build", "stack", "place", "eval", "chain", "optimize")


def pta_breakdown(rep: PerfReport) -> dict:
    """Map "pta"-rooted stages into the canonical joint-PTA breakdown.

    Contract (the ``--smoke --pta`` bench, tests/test_pta.py): named
    components + compile + trace + other account for >= 90% of the PTA
    wall. Counters: `pta_loglike_evals` is every fused joint
    likelihood/gradient evaluation, `pta_chain_steps` is joint
    chain-step draws, `pta_divergences` counts masked divergent HMC
    trajectories, `fleet_stack_reuse` counts member layouts served from
    the padded-stack memo, and `stack_slot_reuse` counts stacked slots
    whose device buffers were reused across a rebuild (fitting/batch.py
    placed_stack — the per-slot invalidation contract)."""
    out = _root_breakdown(rep, "pta", _PTA_COMPONENTS)
    out["pta_loglike_evals"] = int(rep.counters.get("pta_loglike_evals", 0))
    out["pta_chain_steps"] = int(rep.counters.get("pta_chain_steps", 0))
    out["pta_divergences"] = int(rep.counters.get("pta_divergences", 0))
    out["fleet_stack_reuse"] = int(rep.counters.get("fleet_stack_reuse", 0))
    out["stack_slot_reuse"] = int(rep.counters.get("stack_slot_reuse", 0))
    for k in ("pta_psum_bytes_per_eval", "pta_solve_dim"):
        if k in rep.values:
            out[k] = rep.values[k]
    return out


# --- the canonical incremental-refit breakdown -----------------------------------

#: incremental-request sub-stages named in the breakdown (serve/session.py
#: + fitting/incremental.py): the O(k) prepared-column append, the host
#: tensor/fitter rebuild, the rank-k delta-blocks program, the host
#: assemble + p×p solves, the full-data chi² trials and GN polish
#: program, the full-blocks refresh, the finalize tail, and the
#: full-refit fallback wall. Anything else directly under an
#: ``incremental`` stage lands in incremental_other_s.
_INCR_COMPONENTS = ("append", "tensor", "delta", "assemble", "data",
                    "solve", "chi2", "polish", "blocks", "finalize",
                    "full_refit")


def incremental_breakdown(rep: PerfReport) -> dict:
    """Map "incremental"-rooted stages into the canonical incremental
    breakdown. Contract (the ``--smoke --session`` bench, tests/
    test_session.py): named components + compile + trace + other account
    for ≥90% of the incremental wall, so the append-serving telemetry
    cannot silently rot. Counters: ``incremental_refits`` /
    ``incremental_fallbacks`` / ``incremental_rows_appended`` come from
    the engine; ``prepare_rows`` proves the append prepared only k rows.
    """
    wall = 0.0
    comp = {leaf: 0.0 for leaf in _INCR_COMPONENTS}
    nested_ct = {leaf: 0.0 for leaf in _INCR_COMPONENTS}
    compile_s = trace_s = 0.0
    direct = 0.0
    for path, (total, _count) in rep.timings.items():
        segs = path.split("/")
        if "incremental" not in segs:
            continue
        i = segs.index("incremental")
        if len(segs) == i + 1:
            wall += total
        elif len(segs) == i + 2:
            direct += total
            if segs[-1] in comp:
                comp[segs[-1]] += total
        if segs[-1] in ("compile", "trace") and len(segs) > i + 1:
            if segs[-1] == "compile":
                compile_s += total
            else:
                trace_s += total
            if len(segs) > i + 2 and segs[i + 1] in nested_ct:
                nested_ct[segs[i + 1]] += total
    out = {"incremental_wall_s": round(wall, 4)}
    for leaf in _INCR_COMPONENTS:
        # compile/trace nests inside the component that triggered it:
        # subtract so the named fields partition the wall
        out[f"incremental_{leaf}_s"] = round(comp[leaf] - nested_ct[leaf], 4)
    out["incremental_compile_s"] = round(compile_s, 4)
    out["incremental_trace_s"] = round(trace_s, 4)
    out["incremental_other_s"] = round(max(wall - direct, 0.0), 4)
    for c in INCR_COUNTERS:
        out[c] = int(rep.counters.get(c, 0))
    out["prepare_rows"] = int(rep.counters.get("prepare_rows", 0))
    out["prepare_prefix_hits"] = int(
        rep.counters.get("prepare_prefix_hits", 0))
    return out


# --- the canonical campaign breakdown --------------------------------------------

#: campaign sub-stages named in the breakdown (campaign/runner.py): the
#: resume scan (validating durable unit results + replaying the ledger),
#: unit execution (the device work), the crc-framed atomic checkpoint
#: writes (unit results + progress snapshots), and the campaign ledger
#: appends. Anything else directly under a ``campaign`` stage lands in
#: campaign_other_s.
_CAMPAIGN_COMPONENTS = ("resume", "unit", "checkpoint", "ledger")


def campaign_breakdown(rep: PerfReport) -> dict:
    """Map "campaign"-rooted stages into the canonical campaign
    breakdown. Contract (tests/test_campaign.py, the kill-mid-campaign
    drill): named components + compile + trace + other account for
    >= 90% of the campaign wall — preemption-safety telemetry cannot
    silently rot. Counters: ``campaign_units_run`` units executed to a
    durable result, ``campaign_checkpoints`` progress snapshots
    written, ``campaign_resumes`` resumes from durable state."""
    out = _root_breakdown(rep, "campaign", _CAMPAIGN_COMPONENTS)
    for c in ("campaign_units_run", "campaign_checkpoints",
              "campaign_resumes"):
        out[c] = int(rep.counters.get(c, 0))
    return out


# --- bounded streaming quantiles --------------------------------------------------


class QuantileSketch:
    """Bounded-memory streaming quantile estimator (log-bucketed counts).

    A long-lived serving process must report per-request p50/p99 without
    holding every latency sample: this sketch buckets positive values
    into a geometric grid of relative width ``2 * rel_err`` and answers
    quantile queries from the cumulative bucket counts. Memory is
    bounded by the value RANGE (one int per occupied bucket — a few
    hundred buckets span nine decades at 2% resolution) and never by
    the sample count; estimates carry ≤ ``rel_err`` relative error,
    with the exact observed min/max returned at the extremes.
    Thread-safe: the serving engine's worker and client threads feed
    one sketch concurrently.
    """

    def __init__(self, rel_err: float = 0.02, lo: float = 1e-4):
        self._base = math.log1p(2.0 * rel_err)
        self._lo = float(lo)
        self._counts: dict[int, int] = {}
        self._n = 0
        self._min = math.inf
        self._max = -math.inf
        self._sum = 0.0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return self._n

    @property
    def count(self) -> int:
        return self._n

    def _index(self, x: float) -> int:
        if x <= self._lo:
            return 0
        return 1 + int(math.log(x / self._lo) / self._base)

    def add(self, x: float) -> None:
        x = float(x)
        if not math.isfinite(x):
            return
        i = self._index(max(x, 0.0))
        with self._lock:
            self._counts[i] = self._counts.get(i, 0) + 1
            self._n += 1
            self._sum += x
            self._min = min(self._min, x)
            self._max = max(self._max, x)

    def merge(self, other: "QuantileSketch") -> None:
        """Fold another sketch of the SAME grid into this one."""
        if other._base != self._base or other._lo != self._lo:
            raise ValueError("cannot merge QuantileSketches with "
                             "different grids")
        with other._lock:
            counts = dict(other._counts)
            n, s = other._n, other._sum
            mn, mx = other._min, other._max
        with self._lock:
            for i, c in counts.items():
                self._counts[i] = self._counts.get(i, 0) + c
            self._n += n
            self._sum += s
            self._min = min(self._min, mn)
            self._max = max(self._max, mx)

    def to_dict(self) -> dict:
        """JSON-ready marshalled form: the exact grid + bucket counts,
        so a sketch crosses a process boundary (a crash report, a
        recovery twin, a multi-engine fleet rollup) and merges on the
        other side with zero information loss."""
        with self._lock:
            return {
                "base": self._base,
                "lo": self._lo,
                "counts": {str(i): c for i, c in self._counts.items()},
                "n": self._n,
                "sum": self._sum,
                "min": None if self._n == 0 else self._min,
                "max": None if self._n == 0 else self._max,
            }

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileSketch":
        """Inverse of :meth:`to_dict` (bitwise round-trip)."""
        sk = cls()
        sk._base = float(d["base"])
        sk._lo = float(d["lo"])
        sk._counts = {int(i): int(c) for i, c in d["counts"].items()}
        sk._n = int(d["n"])
        sk._sum = float(d["sum"])
        sk._min = math.inf if d["min"] is None else float(d["min"])
        sk._max = -math.inf if d["max"] is None else float(d["max"])
        return sk

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (None while empty). Monotone in q; the
        0/1 extremes return the exact observed min/max."""
        with self._lock:
            if self._n == 0:
                return None
            if q <= 0.0:
                return self._min
            if q >= 1.0:
                return self._max
            target = q * self._n
            seen = 0
            for i in sorted(self._counts):
                seen += self._counts[i]
                if seen >= target:
                    if i == 0:
                        return min(self._lo, self._max)
                    # geometric bucket midpoint, clamped to the observed
                    # envelope so sparse tails cannot overshoot
                    edge = self._lo * math.exp(self._base * (i - 1))
                    mid = edge * math.exp(self._base * 0.5)
                    return min(max(mid, self._min), self._max)
            return self._max  # pragma: no cover — loop always hits target

    @property
    def mean(self) -> float | None:
        with self._lock:
            return (self._sum / self._n) if self._n else None

    def n_buckets(self) -> int:
        """Occupied buckets — the (bounded) memory footprint."""
        with self._lock:
            return len(self._counts)

    def summary(self, unit: str = "ms") -> dict:
        """JSON-ready {count, p50, p90, p99, min, max, mean} block."""
        out = {"count": self.count}
        for name, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
            v = self.quantile(q)
            out[f"{name}_{unit}"] = None if v is None else round(v, 3)
        with self._lock:
            if self._n:
                out[f"min_{unit}"] = round(self._min, 3)
                out[f"max_{unit}"] = round(self._max, 3)
                out[f"mean_{unit}"] = round(self._sum / self._n, 3)
        return out


# --- the canonical serving breakdown ----------------------------------------------

#: serving-engine sub-stages named in the breakdown (serve/engine.py):
#: admission checks + token buckets (`admit`, recorded from the client
#: threads), the worker's bounded wait for work or a lane deadline
#: (`queue`), payload merging of coalesced same-session appends
#: (`coalesce`), lane selection + warm-pool lookups incl. checkpoint
#: restores (`dispatch`), the actual rank-k / batched-fleet device work
#: (`solve`) and result installation + waiter wakeup (`finalize`).
#: Anything else directly under a `serve` stage lands in serve_other_s.
#: journal = write-ahead record appends (serve/journal.py), checkpoint /
#: recover / replay = the durability legs (serve/recover.py): fleet
#: checkpointing, checkpoint restore on recovery, journal-suffix replay.
_SERVE_COMPONENTS = ("admit", "queue", "coalesce", "dispatch", "solve",
                     "finalize", "journal", "checkpoint", "recover",
                     "replay")

#: the canonical serving counter set: every ``serve_*`` counter the
#: engine/scheduler/pool/journal bump. serve_breakdown reports them and
#: the metrics registry (pint_tpu/obs/metrics.py) exports them — the
#: no-orphan gate (tests/test_obs.py) walks the ``perf.add`` call sites
#: and fails when a new counter bypasses either surface.
SERVE_COUNTERS = (
    "serve_requests", "serve_shed", "serve_dispatches",
    "serve_coalesced", "serve_appends", "serve_refits",
    "serve_evictions", "serve_restores",
    "serve_journal_records", "serve_journal_compactions",
    "serve_journal_full",
    "serve_checkpoints", "serve_deadline_expired",
    "serve_retries", "serve_quarantines", "serve_worker_replacements",
    "serve_migrations", "serve_replicas_lost",
    "serve_gateway_requests", "serve_gateway_shed",
)

#: same contract for the incremental-refit counters (serve/session.py +
#: fitting/incremental.py)
INCR_COUNTERS = ("incremental_refits", "incremental_fallbacks",
                 "incremental_rows_appended")


def serve_breakdown(rep: PerfReport) -> dict:
    """Map "serve"-rooted stages into the canonical serving breakdown.

    Contract (the ``--smoke --serve`` bench, tests/test_serve.py): named
    components + compile + trace + other account for ≥90% of the serve
    wall, so the throughput engine's telemetry cannot silently rot.
    Counters: ``serve_requests`` admitted, ``serve_shed`` refused or
    dropped by admission control, ``serve_dispatches`` batches sent to
    the device, ``serve_coalesced`` requests answered by a shared
    coalesced solve, ``serve_appends``/``serve_refits`` answered by
    kind, ``serve_evictions``/``serve_restores`` warm-pool traffic.
    Request-level p50/p99 live in the engine's :class:`QuantileSketch`
    (``ServingEngine.stats()``), not here — the breakdown is wall
    attribution, the sketches are SLO telemetry.
    """
    out = _root_breakdown(rep, "serve", _SERVE_COMPONENTS)
    for c in SERVE_COUNTERS:
        out[c] = int(rep.counters.get(c, 0))
    out["serve_waste_ewma"] = rep.values.get("serve_waste_ewma")
    out["serve_eff_wait_ms"] = rep.values.get("serve_eff_wait_ms")
    # submit-path overhead sketch quantiles (engine.submit_lat), latched
    # per submit while a report is active: the lock-hold tax the
    # two-phase journal append exists to shrink
    out["serve_submit_us_p50"] = rep.values.get("serve_submit_us_p50")
    out["serve_submit_us_p99"] = rep.values.get("serve_submit_us_p99")
    return out


# --- the canonical fit breakdown -------------------------------------------------

#: stage leaves summed into the named breakdown components; everything else
#: under "fit" lands in fit_other_s
_COMPONENTS = ("step", "chi2", "solve", "finalize")


def fit_breakdown(rep: PerfReport) -> dict:
    """Map a report collected around one fit into the canonical breakdown.

    The contract (enforced by the CPU smoke bench, tests/test_perf.py):
    ``fit_compile_s + fit_trace_s + fit_step_s + fit_chi2_s +
    fit_solve_s + fit_finalize_s + fit_other_s == fit_wall_s`` up to
    clock jitter, i.e. the breakdown accounts for the whole measured fit
    wall time. `fit_compile_s` is XLA backend compilation only (what the
    persistent cache eliminates on warm runs); `fit_trace_s` is the host
    Python trace/lowering, which no disk cache can serve.
    """
    t = rep.timings
    wall = rep.seconds("fit")

    def total(leaf):
        return sum(v[0] for p, v in t.items()
                   if p.startswith("fit/") and p.split("/")[-1] == leaf)

    def count(leaf):
        return sum(int(v[1]) for p, v in t.items()
                   if p.startswith("fit/") and p.split("/")[-1] == leaf)

    compile_s = total("compile")
    trace_s = total("trace")
    comp = {leaf: total(leaf) for leaf in _COMPONENTS}
    # trace/compile time nests INSIDE the component that triggered it
    # (e.g. fit/step/compile): subtract it from that component so the
    # named fields partition the wall time instead of double counting
    nested = {
        leaf: sum(v[0] for p, v in t.items()
                  if p.split("/")[-1] in ("compile", "trace")
                  and len(p.split("/")) > 2 and p.split("/")[-2] == leaf
                  and p.startswith("fit/"))
        for leaf in _COMPONENTS
    }
    step_s = comp["step"] - nested["step"]
    chi2_s = comp["chi2"] - nested["chi2"]
    solve_s = comp["solve"] - nested["solve"]
    finalize_s = comp["finalize"] - nested["finalize"]
    # directly-under-fit components account against the wall; deeper
    # nestings (fit/step/host_transfer) are already inside their parent
    top = sum(v[0] for p, v in t.items()
              if len(p.split("/")) == 2 and p.startswith("fit/"))
    other_s = max(wall - top, 0.0)

    n_steps = count("step")
    xfer_bytes = rep.counters.get("host_transfer_bytes", 0)
    xfer_s = sum(v[0] for p, v in t.items()
                 if p.split("/")[-1] == "host_transfer")
    # the fused while_loop path makes ONE step call per fit: attribute
    # per-iteration time to the LM iterations it ran on device
    lm_iters = int(rep.counters.get("lm_iterations", 0))
    iters = lm_iters or n_steps
    aot_hits = int(rep.counters.get("aot_hits", 0))
    aot_fallbacks = int(rep.counters.get("aot_fallbacks", 0))
    compile_wait_s = float(rep.counters.get("compile_wait_s", 0.0))
    # the overlap contract: every program the fit executed was compiled
    # BEFORE the fit needed it (background precompile / warm cache), none
    # fell back to a silent jit recompile, and compile/trace/lock-wait
    # time inside the fit is negligible against the wall (a fit that had
    # to wait out an in-flight background compile only PARTIALLY
    # overlapped — compile_wait_s says by how much it missed)
    overlap_engaged = bool(
        aot_hits > 0 and aot_fallbacks == 0
        and compile_s + trace_s + compile_wait_s < 0.05 * wall + 0.1
    )
    out = {
        "fit_wall_s": round(wall, 4),
        "fit_compile_s": round(compile_s, 4),
        "fit_trace_s": round(trace_s, 4),
        "fit_step_s": round(step_s, 4),
        "n_step_calls": n_steps,
        "per_iter_step_ms": round(step_s / iters * 1e3, 3) if iters else None,
        "fit_chi2_s": round(chi2_s, 4),
        "n_chi2_calls": count("chi2"),
        "fit_solve_s": round(solve_s, 4),
        "fit_finalize_s": round(finalize_s, 4),
        "fit_other_s": round(other_s, 4),
        "solve_path": rep.values.get("solve_path"),
        "solve_path_reason": rep.values.get("solve_path_reason"),
        "lm_iterations": int(rep.counters.get("lm_iterations", 0)),
        "lm_trials": int(rep.counters.get("lm_trials", 0)),
        "lm_rejects": int(rep.counters.get("lm_rejects", 0)),
        "host_transfers": int(rep.counters.get("host_transfers", 0)),
        "host_transfer_bytes": int(xfer_bytes),
        "host_transfer_s": round(xfer_s, 4),
        "host_transfer_MB_per_s": (
            round(xfer_bytes / xfer_s / 1e6, 1) if xfer_s > 0 else None
        ),
        "factorizations": int(rep.counters.get("factorizations", 0)),
        # precompile-overlap + sharded-fit telemetry (fitting/sharded.py):
        # fit_shards = TOA shards of the fused program (1 = single device,
        # None = host-loop path); psum_bytes = estimated per-device
        # collective payload of the fit; while_loop_iters = device loop
        # bodies (linearizations + damping trials) run without a host sync
        "overlap_engaged": overlap_engaged,
        "aot_hits": aot_hits,
        "aot_fallbacks": aot_fallbacks,
        # serialized-executable traffic (ops/compile.py artifact store):
        # hits = programs served by a deserialized executable (zero
        # trace, zero compile); misses = probes that fell back to
        # trace+compile (ledger-visible via the audit block's n_compiles)
        "aot_deserialize_hits": int(
            rep.counters.get("aot_deserialize_hits", 0)),
        "aot_deserialize_misses": int(
            rep.counters.get("aot_deserialize_misses", 0)),
        # the deferred prefit-wRMS residual evaluation (instrument_fit):
        # outside the fit wall, named so the bench's time-to-first-point
        # attribution can account for it on warmed processes
        "prefit_resid_s": round(rep.seconds("prefit_resid"), 4),
        "compile_wait_s": round(compile_wait_s, 4),
        "fit_shards": rep.values.get("fit_shards"),
        "while_loop_iters": int(rep.counters.get("while_loop_iters", 0)),
        "psum_bytes": int(rep.counters.get("psum_bytes", 0)),
        # fleet-fit telemetry (fitting/batch.py): batch_size = fitters in
        # the fleet, batch_shards = mesh shards along the batch axis,
        # bucket_occupancy = datasets per (kind, padded-rows) bucket,
        # padding_waste_frac = fraction of padded rows that are padding,
        # compile_reuse = fits served without a fresh program compile —
        # the amortization is observable, not asserted
        "batch_size": rep.values.get("batch_size"),
        "batch_shards": rep.values.get("batch_shards"),
        "bucket_occupancy": rep.values.get("bucket_occupancy"),
        "padding_waste_frac": rep.values.get("padding_waste_frac"),
        "batch_compiles": int(rep.counters.get("batch_compiles", 0)),
        "compile_reuse": int(rep.counters.get("batch_compile_reuse", 0)),
        # warm-start telemetry (fitting/state.py): whether this fit
        # started from a prior-fit parameter snapshot, and where the
        # snapshot came from ("caller" | a state-file path)
        "warm_start": bool(rep.values.get("warm_start", False)),
        "warm_start_source": rep.values.get("warm_start_source"),
        # which ephemeris served the prepared columns this fit consumed
        # ("analytic" | "kernelpack:..." | "spk:..."): the parity headline
        # is ephemeris-dominated, so a fit result names its source
        "ephemeris_source": rep.values.get("ephemeris_source"),
    }
    # prepare work that ran INSIDE the fit (e.g. a TZR re-prepare in a
    # tensor rebuild) — usually zero; the bench's time-to-first-point
    # attribution assembles the full prepare block from its own scope
    if any("prepare" in p.split("/") for p in t):
        out["prepare"] = prepare_breakdown(rep)
    # compile-time jaxpr-audit ledger (pint_tpu/analysis/): every program
    # the fit lowered, the passes it ran, and any invariant violations —
    # the bench headline carries this block so an audit regression is a
    # bench diff, not a silent warning
    try:
        from pint_tpu.analysis.jaxpr_audit import audit_block

        out["audit"] = audit_block()
    except Exception:  # pragma: no cover — audit must never break a fit  # jaxlint: disable=silent-except — telemetry assembly, not a degradation path
        out["audit"] = None
    # degradation ledger (ops/degrade.py): every corner the pipeline cut
    # to produce this fit — zero clock corrections, stale caches, the
    # analytic-ephemeris fallback, host fallbacks — with timing-error
    # bounds, so a fit result carries its own provenance
    try:
        from pint_tpu.ops.degrade import degradation_block

        out["degradations"] = degradation_block()
    except Exception:  # pragma: no cover — ledger must never break a fit  # jaxlint: disable=silent-except — telemetry assembly, not a degradation path
        out["degradations"] = None
    return out


def instrument_fit(fit_method):
    """Decorator for `fit_toas` implementations: when telemetry is enabled,
    collect a per-fit report around the call and attach the canonical
    breakdown to ``result.perf`` (and ``fitter.last_perf``). Pass-through
    (one bool check) when disabled."""

    @functools.wraps(fit_method)
    def wrapper(self, *args, **kwargs):
        # latch the prefit weighted RMS before the fit moves the params:
        # fitter construction defers it (a fresh-shape resid compile per
        # construction is the append-serving path's dominant cost), and
        # after the fit the residual object reports POSTFIT values
        need_latch = (getattr(self, "_prefit_wrms", False) is None
                      and getattr(self, "result", None) is None)
        if not enabled():
            if need_latch:
                self._prefit_wrms = self.resids.rms_weighted()
            return fit_method(self, *args, **kwargs)
        with collect() as rep:
            if need_latch:
                # staged OUTSIDE the fit wall but inside the report: on a
                # warmed process this first residual evaluation is an AOT
                # deserialize + cache-served compile, and the startup
                # attribution must be able to name it (prefit_resid_s)
                with stage("prefit_resid"):
                    self._prefit_wrms = self.resids.rms_weighted()
            with stage("fit"):
                result = fit_method(self, *args, **kwargs)
        breakdown = fit_breakdown(rep)
        self.last_perf = breakdown
        self.last_perf_report = rep
        if result is not None:
            result.perf = breakdown
        return result

    return wrapper
