"""Backend-aware jit, persistent-compile-cache wiring, and AOT program
handling for extended-precision (dd64/qf32) computations.

CPU fusion history: XLA:CPU's `fusion` pass used to recompute-duplicate
multi-use intermediates when fusing large elementwise DAGs — compensated
arithmetic (two_sum / renorm chains) grew ~2^depth at fusion codegen, and
`precision_jit` disabled the pass for CPU-target programs via per-program
``compiler_options``. The XLA build in the current toolchain has BOTH
fixed the pathology and broken the option: a 17-deep qf_add/qf_mul chain
now compiles+runs in ~1 s with fusion ON and ~15 s with fusion OFF
(measured on a 16-element array; 28-deep: 3.7 s with fusion on), while
passing ``xla_disable_hlo_passes`` through ``compiler_options`` aborts in
jaxlib's env-override application (protobuf: repeated field set through
singular-field reflection). `precision_jit` is therefore plain `jax.jit`
by default everywhere; set ``PINT_TPU_CPU_FUSION_WORKAROUND=1`` to restore
the old per-program pass-disable on toolchains that still need it (guarded
by tests/test_qf32.py's compile-time regression test either way).

This module also owns the fit-path compile machinery the perf layer
(ops/perf.py) reports on:

- `setup_persistent_cache()` wires jax's on-disk XLA compilation cache
  under the shared cache root (utils/cache.py), so a fresh process reuses
  every previously compiled program — the dominant term of the 91 s
  first-fit wall on the flagship bench.
- `TimedProgram` wraps a jitted callable so compile time is split from
  device-step time in the fit breakdown, and exposes `precompile()` for
  the overlap trick: compilation is host-side work that releases the GIL,
  so a worker thread can compile the fit-step program while the chip (or
  the host) is busy with TOA preparation.
- The serialized-AOT-executable artifact store (``PINT_TPU_AOT_EXPORT``):
  the persistent XLA cache eliminates warm-process *compiles* but every
  fresh process still pays the host-Python *trace* of every program —
  the remaining term of the cold-start wall no disk cache served. Every
  AOT-eligible `TimedProgram` (constructed with ``aot_key=``, a
  structural fingerprint of its closure) round-trips its compiled
  executable through a content-addressed artifact beside the compile
  cache, keyed on (label, call signature, device topology, jax/jaxlib/
  XLA versions, source fingerprint, the declared ``aot_key``): a warmed
  process deserializes the executable — zero traces, zero compiles,
  bitwise-identical results — and ``PINT_TPU_EXPECT_WARM=1`` escalates
  any trace/compile that slips through to a strict audit failure (the
  ``pint_tpu warmup`` CLI populates the store for a workload profile).
"""

from __future__ import annotations

import os
import threading
from pathlib import Path

import jax

from pint_tpu.obs import flight, trace as otrace
from pint_tpu.ops import perf
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.compile")

_CPU_WORKAROUND = {"xla_disable_hlo_passes": "fusion"}


def cpu_fusion_compiler_options() -> dict:
    """Per-program compiler options for CPU-target dd/qf programs: empty on
    the current toolchain (see module docstring), the fusion-pass disable
    when PINT_TPU_CPU_FUSION_WORKAROUND=1 opts back in."""
    if knobs.flag("PINT_TPU_CPU_FUSION_WORKAROUND"):
        return dict(_CPU_WORKAROUND)
    return {}


def precision_jit(fn=None, **jit_kwargs):
    """`jax.jit` for functions whose graph contains dd64/qf32 chains.

    Ensures the persistent compilation cache is wired up, and applies the
    CPU fusion workaround when opted in (module docstring)."""
    if fn is None:
        return lambda f: precision_jit(f, **jit_kwargs)
    setup_persistent_cache()
    if jax.default_backend() == "cpu":
        opts = cpu_fusion_compiler_options()
        if opts:
            jit_kwargs.setdefault("compiler_options", opts)
    return jax.jit(fn, **jit_kwargs)


# --- persistent XLA compilation cache -------------------------------------------

_cache_state = {"dir": None, "done": False}
_cache_lock = threading.Lock()


def setup_persistent_cache(force: bool = False) -> str | None:
    """Enable jax's persistent (on-disk) XLA compilation cache.

    The directory is versioned like every other pint_tpu disk cache
    (utils/cache.py): ``$PINT_TPU_CACHE_DIR/xla/jax-<version>`` — jax's own
    cache key covers program/flags/platform, the version directory guards
    against serialization-format drift across toolchains. Idempotent; call
    ``force=True`` to re-apply after changing the env knobs.

    Env: ``PINT_TPU_COMPILE_CACHE`` (the knob documented since the seed:
    a directory overrides the location, ``0`` disables — the graft entry's
    multi-device dryrun relies on the disable because XLA:CPU AOT entries
    written under different detected host features can SIGILL on load);
    ``PINT_TPU_XLA_CACHE=0`` / ``PINT_TPU_XLA_CACHE_DIR`` are equivalent
    split knobs. Cache *errors* never break a fit
    (``jax_raise_persistent_cache_errors`` is set False); a program that
    cannot be cached just compiles normally.

    Returns the cache directory in use, or None when disabled.
    """
    with _cache_lock:
        if _cache_state["done"] and not force:
            return _cache_state["dir"]
        prev_done = _cache_state["done"]
        prev_dir = _cache_state["dir"]
        _cache_state["done"] = True
        legacy = knobs.get("PINT_TPU_COMPILE_CACHE")
        if knobs.get("PINT_TPU_XLA_CACHE") == "0" or legacy == "0":
            _cache_state["dir"] = None
            if prev_done and prev_dir is not None:
                _bump_aot_epoch()
            return None
        from pint_tpu.utils.cache import cache_root

        path = knobs.get("PINT_TPU_XLA_CACHE_DIR") or legacy or str(
            cache_root() / "xla" / f"jax-{jax.__version__}"
        )
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # fit/grid programs compile in 0.5 s - minutes; cache everything
            # that costs more than a disk read
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
            jax.config.update("jax_raise_persistent_cache_errors", False)
            # jax materializes its cache object on the first compile and
            # then ignores jax_compilation_cache_dir updates: if anything
            # compiled before this ran (or a test re-points the dir), the
            # new directory only takes effect after an explicit reset
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:  # pragma: no cover — config surface drift  # jaxlint: disable=silent-except — cache-config drift just disables the compile cache; compile correctness unaffected
            _cache_state["dir"] = None
            return None
        _cache_state["dir"] = path
        # a dir CHANGE must also invalidate every in-process deserialized
        # executable handle: the epoch bump makes TimedProgram drop (and
        # re-resolve from the NEW root) anything it loaded from the old
        # one — a test that swaps PINT_TPU_COMPILE_CACHE mid-session can
        # never be served an executable from the superseded directory
        if prev_done and prev_dir != path:
            _bump_aot_epoch()
        return path


# --- serialized AOT executables (the artifact store) -----------------------------

#: artifact container format; bumped on any layout change so old entries
#: full-key-miss instead of half-loading
_AOT_FORMAT = 1

_aot_lock = threading.Lock()
#: in-process AOT state: ``epoch`` bumps whenever the persistent-cache
#: directory changes, invalidating every deserialized executable handle
#: (TimedProgram drops and re-resolves them); ``override`` is the
#: programmatic enable (None = follow the env knobs).
_aot_state: dict = {"epoch": 0, "override": None}
#: process-wide artifact-store telemetry (aot_block() snapshots it)
_AOT_STATS: dict = {
    "deserialize_hits": 0, "deserialize_misses": 0, "exports": 0,
    "export_failures": 0, "layout_fallbacks": 0,
    "labels": {},  # label -> {"hits": n, "misses": n, "exports": n}
}
#: labels whose executables this backend refused to serialize — tried
#: once, then skipped (the artifact store is an optimization)
_aot_unserializable: set = set()


def _bump_aot_epoch() -> None:
    _aot_state["epoch"] += 1


def aot_epoch() -> int:
    """Monotone counter of persistent-cache-directory changes: a
    deserialized executable handle is only valid within the epoch it was
    loaded in."""
    return _aot_state["epoch"]


def set_aot_export(flag: bool | None) -> None:
    """Programmatic override of the artifact store (None = follow the
    ``PINT_TPU_AOT_EXPORT`` / ``PINT_TPU_EXPECT_WARM`` knobs)."""
    _aot_state["override"] = flag


def aot_enabled() -> bool:
    """True when AOT-eligible programs should round-trip their compiled
    executables through the on-disk artifact store (deserialize-first,
    export-on-compile)."""
    if _aot_state["override"] is not None:
        return bool(_aot_state["override"])
    return (knobs.flag("PINT_TPU_AOT_EXPORT")
            or knobs.flag("PINT_TPU_EXPECT_WARM"))


def aot_cache_dir() -> Path | None:
    """The serialized-executable artifact directory, or None when the
    persistent compile cache is disabled (the artifact store lives
    BESIDE the XLA cache entries and inherits every dir-override /
    disable knob, including the graft dryrun's ``PINT_TPU_COMPILE_CACHE=0``
    host-feature-SIGILL escape hatch)."""
    xla_dir = setup_persistent_cache()
    if xla_dir is None:
        return None
    return Path(xla_dir) / "aot"


def reset_aot_stats() -> None:
    """Zero the artifact-store counters (test isolation)."""
    with _aot_lock:
        _AOT_STATS.update(deserialize_hits=0, deserialize_misses=0,
                          exports=0, export_failures=0, layout_fallbacks=0,
                          labels={})


def aot_note(label: str, event: str) -> None:
    """Record one artifact-store event (``hits``/``misses``/``exports``/
    ``export_failures``/``layout_fallbacks``) process-wide and per label."""
    total_key = {"hits": "deserialize_hits",
                 "misses": "deserialize_misses"}.get(event, event)
    with _aot_lock:
        _AOT_STATS[total_key] += 1
        if event in ("hits", "misses", "exports"):
            per = _AOT_STATS["labels"].setdefault(
                label, {"hits": 0, "misses": 0, "exports": 0})
            per[event] += 1


def aot_block() -> dict:
    """JSON-ready snapshot of the artifact store: deserialize traffic,
    exports, per-label detail and the directory in use — the ``aot``
    block the audit ledger and the bench headline carry."""
    with _aot_lock:
        out = {k: (dict(v) if isinstance(v, dict) else v)
               for k, v in _AOT_STATS.items()}
        out["labels"] = {k: dict(v) for k, v in _AOT_STATS["labels"].items()}
    d = _cache_state["dir"]
    out["cache_dir"] = None if d is None else str(Path(d) / "aot")
    out["enabled"] = aot_enabled()
    return out


def _aot_topology() -> str:
    """Device-topology key component: an executable is loadable only onto
    the client layout it was compiled for (device count/kind/process
    layout; the XLA platform version guards serialized-binary drift)."""
    devs = jax.devices()
    kinds = ",".join(f"{d.platform}:{getattr(d, 'device_kind', '?')}"
                     for d in devs)
    try:
        plat = devs[0].client.platform_version
    except Exception:  # pragma: no cover — client API drift  # jaxlint: disable=silent-except — version component degrades to '?'; the jax/jaxlib components still key the artifact
        plat = "?"
    return (f"{jax.default_backend()};n={len(devs)};"
            f"procs={jax.process_count()};{kinds};xla={plat}")


def _aot_full_key(label: str, sig, collective_axes, aot_key: str) -> str:
    """The FULL content key stored inside an artifact and compared on
    load (a truncated-filename-hash collision is a miss, never a wrong
    executable). Components: container format, program label, jax +
    jaxlib + XLA-platform versions, the package source fingerprint (any
    source change conservatively invalidates — the traced program is a
    function of the source), device topology, declared collective axes,
    the caller's structural ``aot_key`` (what the closure bakes in), and
    the exact call signature (treedef + shapes/dtypes/weak_type)."""
    import jaxlib

    from pint_tpu.utils.cache import source_fingerprint

    treedef, leaves = sig
    return "\n".join([
        f"format={_AOT_FORMAT}",
        f"label={label}",
        f"jax={jax.__version__}",
        f"jaxlib={getattr(jaxlib, '__version__', '?')}",
        f"src={source_fingerprint()}",
        f"topo={_aot_topology()}",
        f"axes={','.join(collective_axes)}",
        f"extra={aot_key}",
        f"tree={treedef}",
        f"leaves={leaves}",
    ])


def _aot_path(label: str, key: str) -> Path | None:
    import hashlib

    d = aot_cache_dir()
    if d is None:
        return None
    safe = "".join(c if (c.isalnum() or c in "-_") else "_" for c in label)
    return d / f"{safe}-{hashlib.sha256(key.encode()).hexdigest()[:24]}.aotx"


#: artifact container: magic + little-endian u32 header length + JSON
#: header (format/key/label) + the `jax.export` serialized module bytes.
#: No pickle anywhere in the load path — a tampered artifact can at worst
#: fail deserialization (quarantine), never execute host code.
_AOT_MAGIC = b"PINTAOT1"


def _aot_write_file(path: Path, header: dict, blob: bytes) -> None:
    import json
    import struct

    h = json.dumps(header).encode()
    os.makedirs(path.parent, exist_ok=True)
    tmp = path.with_suffix(f".tmp{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(_AOT_MAGIC)
        f.write(struct.pack("<I", len(h)))
        f.write(h)
        f.write(blob)
    tmp.replace(path)


def _aot_read_file(path: Path) -> tuple[dict, bytes]:
    import json
    import struct

    with open(path, "rb") as f:
        magic = f.read(len(_AOT_MAGIC))
        if magic != _AOT_MAGIC:
            raise ValueError(f"bad AOT artifact magic {magic!r}")
        (hlen,) = struct.unpack("<I", f.read(4))
        header = json.loads(f.read(hlen).decode())
        blob = f.read()
    return header, blob


_export_registered = [False]


def _ensure_export_registrations() -> None:
    """Register the package's NamedTuple pytree carriers with
    `jax.export`'s treedef serializer (stable dotted names, so an
    artifact written by one process reconstructs the identical call/
    result trees in another). Idempotent; unknown future carriers only
    cost an export failure for that one program, never a wrong load."""
    if _export_registered[0]:
        return
    _export_registered[0] = True
    from jax import export as _jexport

    from pint_tpu.fitting.sharded import FusedFitResult
    from pint_tpu.ops.dd import DD
    from pint_tpu.ops.qf32 import QF

    for t in (DD, QF, FusedFitResult):
        try:
            _jexport.register_namedtuple_serialization(
                t, serialized_name=f"{t.__module__}.{t.__qualname__}")
        except ValueError:  # pragma: no cover — double registration  # jaxlint: disable=silent-except — already-registered is the idempotent success case
            pass
    # XLA:CPU lapack custom calls resolve scipy's BLAS/LAPACK function
    # pointers LAZILY: jax's own lowering shims call _lapack.initialize()
    # on first use, but a DESERIALIZED module bypasses those shims
    # entirely — executing its lapack_*_ffi custom call with unresolved
    # pointers segfaults. Importing jaxlib.lapack registers the targets;
    # initialize() binds the pointers (idempotent, a few µs).
    if jax.default_backend() == "cpu":
        try:
            import jaxlib.lapack as _jl_lapack

            _jl_lapack._lapack.initialize()
        except Exception as e:  # pragma: no cover — jaxlib layout drift  # jaxlint: disable=silent-except — missing initializer only matters for deserialized lapack calls; the failure is logged and those programs fall back to trace+compile on their first (crashing-free) jit dispatch
            log.warning(f"could not initialize CPU lapack kernels for "
                        f"deserialized executables: {e}")


def _aot_load_exe(label: str, key: str, args):
    """Deserialize one artifact and AOT-compile its embedded module, or
    None on miss. The PR-6/7 cache discipline: the stored full key must
    equal the computed one (a truncated-filename-hash collision or any
    version skew is a MISS, never a wrong executable); a corrupt or
    unreadable entry is QUARANTINED beside the store with a
    ``fetch.corrupt_quarantined`` ledger event and the program recompiles
    cleanly.

    The artifact carries the `jax.export` StableHLO module — portable
    across processes by construction (custom-call targets referenced by
    name, no baked host pointers). Loading traces only the tiny
    `Exported.call` wrapper (never the model Python) and the XLA compile
    of the embedded module is served by the persistent compile cache the
    warmup run already populated — zero model traces, cache-served
    compile."""
    path = _aot_path(label, key)
    if path is None or not path.exists():
        return None
    try:
        header, blob = _aot_read_file(path)
        if header.get("format") != _AOT_FORMAT or header.get("key") != key:
            # full-key mismatch: version skew / hash collision — a miss,
            # never a wrong executable
            log.info(f"AOT artifact key mismatch for {path.name}; "
                     "recompiling")
            return None
        from jax import export as _jexport

        _ensure_export_registrations()
        exported = _jexport.deserialize(bytearray(blob))
        return jax.jit(exported.call).lower(*args).compile()
    except Exception as e:  # noqa: BLE001 — corrupt artifact: quarantine + recompile
        from pint_tpu.ops import degrade

        qdir = path.parent / "quarantine"
        try:
            os.makedirs(qdir, exist_ok=True)
            os.replace(path, qdir / path.name)
        except OSError:
            pass
        degrade.record(
            "fetch.corrupt_quarantined", "aot_executable",
            f"corrupt serialized executable {path.name} quarantined ({e}); "
            "recompiling from source",
            bound_us=0.0,  # full recovery: the program recompiles
            fix="delete the quarantined entry after diagnosis; the store "
                "re-populates on the next compile",
        )
        return None


def _aot_store(label: str, key: str, jfn, args) -> bool:
    """Export one freshly-compiled program into the artifact store
    (`jax.export` serialization, atomic replace, LRU prune). Failures
    only cost the next process a retrace; a program the exporter refuses
    is tried once per label."""
    if label in _aot_unserializable:
        return False
    path = _aot_path(label, key)
    if path is None:
        return False
    try:
        from jax import export as _jexport

        _ensure_export_registrations()
        blob = bytes(_jexport.export(jfn)(*args).serialize())
        _aot_write_file(path, {"format": _AOT_FORMAT, "key": key,
                               "label": label, "jax": jax.__version__},
                        blob)
        aot_note(label, "exports")
        perf.add("aot_exports", 1)
        keep = int(knobs.get("PINT_TPU_AOT_CACHE_KEEP") or 0)
        if keep > 0:
            entries = sorted(path.parent.glob("*.aotx"), key=os.path.getmtime)
            for old in entries[:-keep]:
                old.unlink(missing_ok=True)
        return True
    except Exception as e:  # noqa: BLE001  # jaxlint: disable=silent-except — the artifact store is an optimization; an unserializable program only costs the next process a retrace and the miss is logged once per label
        _aot_unserializable.add(label)
        aot_note(label, "export_failures")
        log.warning(f"could not serialize AOT executable for {label!r}: {e}")
        return False


def _expect_warm_trace(label: str, sig) -> None:
    """The retrace-zero contract: under ``PINT_TPU_EXPECT_WARM=1`` a
    TimedProgram that is about to trace+compile (the artifact store had
    no matching entry) records a ledger-visible ``expect-warm`` violation
    and raises — a warmed process performs ZERO traces, so any compile
    event is a warmup-coverage bug, not a performance detail."""
    if not knobs.flag("PINT_TPU_EXPECT_WARM"):
        return
    from pint_tpu.analysis.jaxpr_audit import expect_warm_violation

    expect_warm_violation(
        label,
        f"program {label!r} had to trace+compile under "
        "PINT_TPU_EXPECT_WARM=1 (no serialized executable matched this "
        "signature) — the warmup profile did not cover this program; "
        "re-run `pint_tpu warmup` with a matching (model-skeleton, "
        f"dataset-shape) profile [sig={sig!r}]",
    )


# --- AOT program wrapper ---------------------------------------------------------


def canonicalize_params(params):
    """Give every plain Python-float parameter leaf a concrete, strongly
    typed f64 aval.

    A Python float traces as a WEAK-typed scalar; after the first
    `apply_delta` the same leaf is a strong f64 array, which is a
    different abstract value — so the step and phase programs were being
    traced AND compiled twice per first fit (measured: the duplicate
    compile was a full second copy of the fit-step compile cost).
    Canonicalizing up front makes iteration 1 and iteration N share one
    program. Ints/bools are left alone: promoting them would change the
    program's dtype semantics."""
    import jax.numpy as jnp

    def canon(x):
        if type(x) is float:
            return jnp.asarray(x, dtype=jnp.float64)
        return x

    return jax.tree_util.tree_map(canon, params)


def _args_signature(args):
    """Hashable (treedef, leaf shapes/dtypes/weak_type) signature of a call.

    weak_type is part of a leaf's abstract value: an executable lowered
    for a strong f64 scalar rejects a weak-typed call operand, and the
    silent jit fallback then recompiles the whole program — exactly the
    overlap miss the flagship bench measured (satellite: BENCH_r05
    `fit_plus_compile_overlap_s == initial_fit_s`). Distinguishing the
    two here makes precompile signatures honest."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return treedef, tuple(
        (tuple(getattr(x, "shape", ())),
         str(getattr(x, "dtype", type(x).__name__)),
         bool(getattr(x, "weak_type", False)))
        for x in leaves
    )


class TimedProgram:
    """Jitted-callable wrapper: compile-time split + ahead-of-time compile.

    - With telemetry collecting (ops/perf.py), the first call per argument
      signature explicitly lowers+compiles under a ``compile`` stage, so
      the fit breakdown separates `fit_compile_s` from device-step time;
      execution is blocked-on so the enclosing stage measures real device
      time rather than async dispatch.
    - `precompile(*args)` compiles the executable ahead of the first call
      (safe from a worker thread — XLA compilation releases the GIL), so
      a later first call finds it ready.
    - With telemetry off and nothing precompiled, calls pass straight
      through to the jitted callable.
    - Every lowering is run through the jaxpr auditor
      (pint_tpu/analysis/jaxpr_audit.py) before it compiles:
      ``collective_axes`` declares the mesh axes whose collectives the
      program MUST contain (empty = no collective may appear, the
      1-device contract), ``canonical=True`` (the default — every fit
      program takes canonicalized operands) arms the retrace-budget
      pass, and ``precision_spec`` declares the extended-precision
      discipline (``"dd64"`` / ``"qf32"`` / ``"f64"`` or a
      :class:`~pint_tpu.analysis.ddflow.PrecisionSpec`) that arms the
      dd-flow dataflow passes — a program carrying dd operands with no
      spec draws a warn-level ``dd-spec`` audit event.
      ``PINT_TPU_AUDIT=strict`` turns violations into compile-time
      errors; ``=0`` skips the audit.
    - Each audited lowering also lands in the static cost ledger
      (pint_tpu/analysis/costmodel.py): FLOPs, bytes moved, collective
      bytes and peak live buffer bytes per program label — the numbers
      ``python -m pint_tpu.analysis.cost --check`` gates against the
      checked-in budgets.
    - ``aot_key`` (a string) marks the program AOT-SERIALIZABLE: its
      closure content is fully described by (label, call signature,
      source fingerprint, aot_key), so the compiled executable may be
      exported to / deserialized from the on-disk artifact store when
      ``PINT_TPU_AOT_EXPORT=1`` (zero-trace warm starts; the
      ``aot_deserialize_hits`` counter and the ledger's ``aot`` block
      report the traffic). ``aot_key=None`` (the default) opts out — a
      program whose closure bakes data the key cannot see (e.g. the
      memoized MCMC posterior) must never be served cross-process.
    """

    __slots__ = ("jfn", "label", "collective_axes", "canonical",
                 "precision_spec", "aot_key", "donate_invars", "_exes",
                 "_disk_sigs", "_bad_sigs", "_lock")

    def __init__(self, jfn, label: str,
                 collective_axes: tuple[str, ...] = (),
                 canonical: bool = True,
                 precision_spec=None,
                 aot_key: str | None = None,
                 donate_invars: tuple[int, ...] = ()):
        self.jfn = jfn
        self.label = label
        self.collective_axes = tuple(collective_axes)
        self.canonical = canonical
        self.precision_spec = precision_spec
        self.aot_key = aot_key
        #: flat jaxpr invar indices the wrapped jit donates
        #: (``donate_argnums`` on a flat-array signature): the cost model
        #: credits the input-output aliasing so the ledger's peak_bytes
        #: reflects the in-place update instead of a doubled buffer
        self.donate_invars = tuple(donate_invars)
        self._exes: dict = {}
        # sig -> aot_epoch at deserialization time: a persistent-cache
        # dir change invalidates these handles (never compiled ones)
        self._disk_sigs: dict = {}
        # signatures whose AOT executable rejected its operands once
        # (layout/sharding mismatch): latched sticky so the failing
        # dispatch is never paid again — one fit.aot_layout_fallback
        # degradation event, then the plain jit path per call
        self._bad_sigs: set = set()
        self._lock = threading.Lock()

    # deepcopy-atomic, like the bare jit wrappers these replace: model
    # deepcopies share the compiled-program cache entries by reference
    # (the programs depend only on model STRUCTURE, which the copy shares)
    def __deepcopy__(self, memo):
        return self

    def __copy__(self):
        return self

    def precompile(self, *args) -> None:
        sig = _args_signature(args)
        self._evict_stale_disk_exes()
        if sig not in self._exes:
            self._compile(sig, args)

    def _evict_stale_disk_exes(self) -> None:
        """Drop deserialized executable handles loaded under a superseded
        persistent-cache directory (setup_persistent_cache dir change):
        the next call re-resolves against the NEW artifact root instead
        of silently serving an executable from the old one."""
        if not self._disk_sigs:
            return
        epoch = aot_epoch()
        with self._lock:
            stale = [s for s, e in self._disk_sigs.items() if e != epoch]
            for s in stale:
                self._exes.pop(s, None)
                self._disk_sigs.pop(s, None)

    def _try_deserialize(self, sig, args):
        """One artifact-store probe for this (label, signature): the
        deserialized executable on a full-key hit, else None (the miss is
        counted — a warmup-coverage gap must be ledger-visible)."""
        key = _aot_full_key(self.label, sig, self.collective_axes,
                            self.aot_key)
        # traced + flight-noted: a deserialize triggered mid-request is
        # attributed to the request whose dispatch needed it (the worker
        # attaches the batch's trace id around the dispatch)
        with perf.stage("aot_load"), \
                otrace.span(f"aot_load:{self.label}"):
            exe = _aot_load_exe(self.label, key, args)
        if exe is not None:
            aot_note(self.label, "hits")
            perf.add("aot_deserialize_hits", 1)
            flight.note("aot_load", label=self.label,
                        trace=otrace.current_trace_id())
        else:
            aot_note(self.label, "misses")
            perf.add("aot_deserialize_misses", 1)
        return exe

    def _compile(self, sig, args):
        """(executable, compiled_here): compiled_here is False when another
        thread's in-flight compile of the same signature was waited out —
        that wait is recorded (``compile_wait_s``) so a partially-overlapped
        background precompile shows up in the fit breakdown instead of
        hiding inside the enclosing stage — or when the executable was
        DESERIALIZED from the artifact store instead of compiled."""
        import time as _time

        t0 = _time.perf_counter()
        with self._lock:
            exe = self._exes.get(sig)
            if exe is None and self.aot_key is not None and aot_enabled():
                exe = self._try_deserialize(sig, args)
                if exe is not None:
                    self._exes[sig] = exe
                    self._disk_sigs[sig] = aot_epoch()
                    return exe, False
            if exe is None:
                # the retrace-zero contract binds HERE: a warmed process
                # must never reach the trace below
                _expect_warm_trace(self.label, sig)
                from pint_tpu.analysis.jaxpr_audit import record_compile

                record_compile(self.label)
                # observability: the compile event lands in the flight
                # ring and, when a request trace is attached (the serve
                # worker's dispatch), as a span on THAT request — the
                # operator sees which request paid for which compile
                flight.note("compile", label=self.label,
                            trace=otrace.current_trace_id())
                with otrace.span(f"compile:{self.label}"):
                    # trace (host Python, never cached) split from backend
                    # compile (XLA, served from the persistent cache when
                    # warm)
                    with perf.stage("trace"):
                        traced = None
                        if hasattr(self.jfn, "trace"):
                            try:
                                traced = self.jfn.trace(*args)
                            except Exception:  # pragma: no cover — stage API drift  # jaxlint: disable=silent-except — trace-API drift falls back to lower(); same program, attribution only
                                traced = None
                        lowered = (traced.lower() if traced is not None
                                   else self.jfn.lower(*args))
                    from pint_tpu.analysis.jaxpr_audit import audit_program

                    closed = None if traced is None else traced.jaxpr
                    audit_program(
                        self.label,
                        closed,
                        args,
                        collective_axes=self.collective_axes,
                        canonical=self.canonical,
                        prior_sigs=tuple(self._exes.keys()),
                        sig=sig,
                        program_id=id(self),
                        spec=self.precision_spec,
                    )
                    if closed is not None:
                        # static cost ledger (analysis/costmodel.py):
                        # every lowering's FLOPs/bytes land beside the
                        # audit block
                        from pint_tpu.analysis import costmodel

                        costmodel.record_program(
                            self.label, closed,
                            donate_invars=self.donate_invars)
                    with perf.stage("compile"):
                        exe = lowered.compile()
                        if self.aot_key is not None and aot_enabled():
                            # export rides the compile stage: the
                            # serialize cost is compile-shaped work and
                            # must stay inside the named fit_compile_s
                            # attribution
                            _aot_store(self.label,
                                       _aot_full_key(self.label, sig,
                                                     self.collective_axes,
                                                     self.aot_key),
                                       self.jfn, args)
                perf.add(f"compiled:{self.label}", 1)
                self._exes[sig] = exe
                return exe, True
        wait = _time.perf_counter() - t0
        if wait > 1e-3:
            perf.add("compile_wait_s", wait)
        return exe, False

    def __call__(self, *args):
        collecting = perf.active()
        aot = self.aot_key is not None and aot_enabled()
        if (not self._exes and not collecting and not aot
                and not self.donate_invars):
            # donating programs never take this bypass: the donated
            # input-output aliasing is part of the cost-ledger contract
            # (no doubled peak), which only the _compile path records
            return self.jfn(*args)
        self._evict_stale_disk_exes()
        sig = _args_signature(args)
        if sig in self._bad_sigs:
            # sticky layout fallback (one degradation event already
            # recorded): skip the known-failing AOT dispatch entirely
            perf.add("aot_fallbacks", 1)
            out = self.jfn(*args)
            if collecting:
                out = jax.block_until_ready(out)
            return out
        exe = self._exes.get(sig)
        compiled_here = False
        if exe is None:
            if not collecting and not aot and not self.donate_invars:
                return self.jfn(*args)
            exe, compiled_here = self._compile(sig, args)
        try:
            out = exe(*args)
            if not compiled_here:
                # served by an executable compiled BEFORE this call
                # (precompile overlap, a deserialized artifact, or an
                # earlier iteration): overlap_engaged keys on this
                perf.add("aot_hits", 1)
        except Exception as e:  # jaxlint: disable=silent-except — AOT layout mismatch re-dispatches through jit — latched sticky + one fit.aot_layout_fallback ledger event
            # AOT executables are stricter than jit (layout/sharding of the
            # exact lowering); a mismatch falls back to the jit path,
            # latched per signature so the failing dispatch is paid ONCE
            perf.add("aot_fallbacks", 1)
            self._bad_sigs.add(sig)
            aot_note(self.label, "layout_fallbacks")
            from pint_tpu.ops import degrade

            degrade.record(
                "fit.aot_layout_fallback", self.label,
                "AOT executable rejected its call operands "
                f"(layout/sharding mismatch: {type(e).__name__}); this "
                "signature re-dispatches through jit from now on",
                bound_us=0.0,  # accuracy preserved; dispatch cost degraded
                fix="re-run pint_tpu warmup on THIS device topology, or "
                    "clear the AOT artifact dir so the executable is "
                    "rebuilt for the current layout",
            )
            out = self.jfn(*args)
        if collecting:
            out = jax.block_until_ready(out)
        return out


def use_host_solve() -> bool:
    """True when the fitters' small dense linear algebra (SVD/eigh/
    Cholesky, Woodbury assembly) must run on the host / in-process CPU
    backend: non-CPU backends emulate f64 with f32 exponent RANGE, and
    factorizations of ill-conditioned matrices underflow to NaN on device
    (measured for both the WLS design-matrix SVD and the GLS red-noise
    Woodbury pieces). ``PINT_TPU_HOST_SOLVE=1`` forces it on CPU so tests
    exercise the host path."""
    return (jax.default_backend() != "cpu"
            or knobs.flag("PINT_TPU_HOST_SOLVE"))


def _tree_nbytes(obj) -> int:
    return sum(getattr(x, "nbytes", 0) for x in jax.tree_util.tree_leaves(obj))


def host_transfer(obj, device=None):
    """Move a pytree to the host/CPU device, counted and timed for the fit
    breakdown (host_transfers / host_transfer_bytes counters + the
    ``host_transfer`` stage)."""
    import numpy as np

    collecting = perf.active()
    with perf.stage("host_transfer"):
        if device is None:
            out = jax.tree_util.tree_map(np.asarray, obj)
        else:
            out = jax.device_put(obj, device)
            if collecting:
                out = jax.block_until_ready(out)
    if collecting:
        perf.add("host_transfers", 1)
        perf.add("host_transfer_bytes", _tree_nbytes(obj))
    return out


def cpu_transfer_memo():
    """Single-slot per-tag device->CPU transfer memo.

    The fitters' host-solve paths move the (large, constant-per-fit) TOA
    tensor to the CPU backend once per object rather than on every LM
    trial. The slot holds a STRONG reference to the keyed object, so
    ``is``-identity can never alias a recycled id() of a garbage-collected
    tensor (the memo outlives any one fitter — it hangs off the model's
    step-fn cache)."""
    cpu = jax.devices("cpu")[0]
    slots: dict = {}

    def put(tag, obj):
        keyed, cached = slots.get(tag, (None, None))
        if keyed is not obj:
            cached = host_transfer(obj, cpu)
            slots[tag] = (obj, cached)
        return cached

    return put


def model_cpu_memo(model):
    """One shared CPU-transfer memo per model: the GLS/wideband step and
    chi^2 closures all move the same TOA tensor, so sharing the memo
    halves the transfers. Retention is BOUNDED: one (device, CPU) tensor
    pair per tag, replaced on the next fit with a different tensor —
    weakref slots are not an option because tensor pytrees are plain
    dicts (not weakref-able)."""
    return model.__dict__.setdefault("_cpu_transfer_memo", cpu_transfer_memo())


def adaptive_fused(fused_fn, host_fn, is_good, label: str,
                   forced: bool | None = None, precompile=None):
    """Fused-device-first dispatcher with sticky host fallback.

    Calls `fused_fn` (the fully on-device program) and returns its result
    when `is_good(out)`; otherwise recomputes through `host_fn` (device
    physics + host/CPU dense solve). When the host result is good after a
    fused failure, the failure was device underflow — structural for the
    model, not the trial point — so subsequent calls skip the wasted
    device pass. On the CPU backend (PINT_TPU_HOST_SOLVE test mode) the
    host path is used unconditionally; `forced` overrides the backend
    check (tests exercise the latch logic on CPU with forced=False).

    The returned callable carries its dispatch telemetry as attributes —
    ``solve_path`` ("fused" | "host", the sticky outcome), ``last_path``
    (the path the most recent call used) and ``latch_reason`` (why the
    host path latched) — and latches the same into any collecting perf
    report. `precompile`, when given, is exposed as ``call.precompile``
    so fitter-level AOT warmup reaches the right underlying programs.
    """
    if forced is None:
        forced = jax.default_backend() == "cpu"
    state = {"skip_fused": False, "reason": "forced_host" if forced else None}

    def _note(path):
        # refresh the callable's telemetry attributes + latch into any
        # collecting perf report
        call.last_path = path
        call.solve_path = "host" if (forced or state["skip_fused"]) else "fused"
        call.latch_reason = state["reason"]
        perf.put("solve_path", call.solve_path)
        perf.put("solve_path_reason", state["reason"])

    def call(*args):
        if not forced and not state["skip_fused"]:
            out = fused_fn(*args)
            # fault-injection site: tier-1 drives the sticky fallback on
            # any backend by NaN-poisoning the fused program's output
            from pint_tpu.testing import faults

            out = faults.poison_nonfinite("fit.step", out, label)
            if is_good(out):
                _note("fused")
                return out
            host_out = host_fn(*args)
            from pint_tpu.ops import degrade

            if is_good(host_out):
                state["skip_fused"] = True
                state["reason"] = "device_nonfinite_host_clean"
                degrade.record(
                    "fit.host_fallback", label,
                    "on-device result non-finite but host result clean "
                    "(device underflow) — using the host path from now on",
                    bound_us=0.0,  # accuracy preserved; throughput degraded
                    fix="condition the model (fewer degenerate params) or "
                        "run the solve on a true-f64 backend",
                )
            else:
                # NOT a degradation: both paths agree the trial point is
                # bad; run_lm's backtracking handles it (no ledger write)
                state["reason"] = "both_paths_nonfinite"
            _note("host")
            return host_out
        _note("host")
        return host_fn(*args)

    call.state = state
    call.last_path = None
    call.solve_path = "host" if forced else "fused"
    call.latch_reason = state["reason"]
    if precompile is not None:
        call.precompile = precompile
    return call
