"""Backend-aware jit for extended-precision (dd64/qf32) computations.

XLA:CPU's `fusion` pass (jax 0.9.0) recompute-duplicates multi-use
intermediates when it fuses large elementwise DAGs. Compensated arithmetic
(two_sum / renorm chains) is exactly that shape: every error term is used
twice, so the emitted code grows ~2^depth. Measured on a 16-element array:
a 15-deep qf_add/qf_mul chain runs in 2 ms, 16-deep in 0.4 s, 17-deep in
>100 s — while the *optimized HLO is the same size*; the duplication happens
at fusion codegen. The TPU compiler does not have this pathology (32-deep
chain: 0.1 ms), and `lax.optimization_barrier` is stripped by the CPU
pipeline before fusion, so the only effective cure is disabling the CPU
fusion pass for the affected programs.

`precision_jit` therefore compiles with
`compiler_options={"xla_disable_hlo_passes": "fusion"}` when (and only
when) the computation targets the CPU backend. The option is scoped to the
single jitted program — nothing leaks into TPU compiles, where disabling
fusion would be a real performance loss.
"""

from __future__ import annotations

import jax

_CPU_WORKAROUND = {"xla_disable_hlo_passes": "fusion"}


def precision_jit(fn=None, **jit_kwargs):
    """`jax.jit` for functions whose graph contains dd64/qf32 chains.

    On the CPU backend, disables the XLA fusion pass for this program (see
    module docstring); elsewhere it is plain `jax.jit`.
    """
    if fn is None:
        return lambda f: precision_jit(f, **jit_kwargs)
    if jax.default_backend() == "cpu":
        jit_kwargs.setdefault("compiler_options", _CPU_WORKAROUND)
    return jax.jit(fn, **jit_kwargs)


def use_host_solve() -> bool:
    """True when the fitters' small dense linear algebra (SVD/eigh/
    Cholesky, Woodbury assembly) must run on the host / in-process CPU
    backend: non-CPU backends emulate f64 with f32 exponent RANGE, and
    factorizations of ill-conditioned matrices underflow to NaN on device
    (measured for both the WLS design-matrix SVD and the GLS red-noise
    Woodbury pieces). ``PINT_TPU_HOST_SOLVE=1`` forces it on CPU so tests
    exercise the host path."""
    import os

    return (jax.default_backend() != "cpu"
            or os.environ.get("PINT_TPU_HOST_SOLVE", "0") == "1")


def cpu_transfer_memo():
    """Single-slot per-tag device->CPU transfer memo.

    The fitters' host-solve paths move the (large, constant-per-fit) TOA
    tensor to the CPU backend once per object rather than on every LM
    trial. The slot holds a STRONG reference to the keyed object, so
    ``is``-identity can never alias a recycled id() of a garbage-collected
    tensor (the memo outlives any one fitter — it hangs off the model's
    step-fn cache)."""
    cpu = jax.devices("cpu")[0]
    slots: dict = {}

    def put(tag, obj):
        keyed, cached = slots.get(tag, (None, None))
        if keyed is not obj:
            cached = jax.device_put(obj, cpu)
            slots[tag] = (obj, cached)
        return cached

    return put


def model_cpu_memo(model):
    """One shared CPU-transfer memo per model: the GLS/wideband step and
    chi^2 closures all move the same TOA tensor, so sharing the memo
    halves the transfers. Retention is BOUNDED: one (device, CPU) tensor
    pair per tag, replaced on the next fit with a different tensor —
    weakref slots are not an option because tensor pytrees are plain
    dicts (not weakref-able)."""
    return model.__dict__.setdefault("_cpu_transfer_memo", cpu_transfer_memo())


def adaptive_fused(fused_fn, host_fn, is_good, label: str):
    """Fused-device-first dispatcher with sticky host fallback.

    Calls `fused_fn` (the fully on-device program) and returns its result
    when `is_good(out)`; otherwise recomputes through `host_fn` (device
    physics + host/CPU dense solve). When the host result is good after a
    fused failure, the failure was device underflow — structural for the
    model, not the trial point — so subsequent calls skip the wasted
    device pass. On the CPU backend (PINT_TPU_HOST_SOLVE test mode) the
    host path is used unconditionally."""
    import logging

    forced = jax.default_backend() == "cpu"
    state = {"skip_fused": False}

    def call(*args):
        if not forced and not state["skip_fused"]:
            out = fused_fn(*args)
            if is_good(out):
                return out
            host_out = host_fn(*args)
            if is_good(host_out):
                state["skip_fused"] = True
                logging.getLogger("pint_tpu.fitting").info(
                    f"{label}: on-device result non-finite but host result "
                    "clean (device underflow) — using the host path from now on"
                )
            return host_out
        return host_fn(*args)

    return call
