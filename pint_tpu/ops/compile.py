"""Backend-aware jit, persistent-compile-cache wiring, and AOT program
handling for extended-precision (dd64/qf32) computations.

CPU fusion history: XLA:CPU's `fusion` pass used to recompute-duplicate
multi-use intermediates when fusing large elementwise DAGs — compensated
arithmetic (two_sum / renorm chains) grew ~2^depth at fusion codegen, and
`precision_jit` disabled the pass for CPU-target programs via per-program
``compiler_options``. The XLA build in the current toolchain has BOTH
fixed the pathology and broken the option: a 17-deep qf_add/qf_mul chain
now compiles+runs in ~1 s with fusion ON and ~15 s with fusion OFF
(measured on a 16-element array; 28-deep: 3.7 s with fusion on), while
passing ``xla_disable_hlo_passes`` through ``compiler_options`` aborts in
jaxlib's env-override application (protobuf: repeated field set through
singular-field reflection). `precision_jit` is therefore plain `jax.jit`
by default everywhere; set ``PINT_TPU_CPU_FUSION_WORKAROUND=1`` to restore
the old per-program pass-disable on toolchains that still need it (guarded
by tests/test_qf32.py's compile-time regression test either way).

This module also owns the fit-path compile machinery the perf layer
(ops/perf.py) reports on:

- `setup_persistent_cache()` wires jax's on-disk XLA compilation cache
  under the shared cache root (utils/cache.py), so a fresh process reuses
  every previously compiled program — the dominant term of the 91 s
  first-fit wall on the flagship bench.
- `TimedProgram` wraps a jitted callable so compile time is split from
  device-step time in the fit breakdown, and exposes `precompile()` for
  the overlap trick: compilation is host-side work that releases the GIL,
  so a worker thread can compile the fit-step program while the chip (or
  the host) is busy with TOA preparation.
"""

from __future__ import annotations

import os
import threading

import jax

from pint_tpu.ops import perf
from pint_tpu.utils import knobs

_CPU_WORKAROUND = {"xla_disable_hlo_passes": "fusion"}


def cpu_fusion_compiler_options() -> dict:
    """Per-program compiler options for CPU-target dd/qf programs: empty on
    the current toolchain (see module docstring), the fusion-pass disable
    when PINT_TPU_CPU_FUSION_WORKAROUND=1 opts back in."""
    if knobs.flag("PINT_TPU_CPU_FUSION_WORKAROUND"):
        return dict(_CPU_WORKAROUND)
    return {}


def precision_jit(fn=None, **jit_kwargs):
    """`jax.jit` for functions whose graph contains dd64/qf32 chains.

    Ensures the persistent compilation cache is wired up, and applies the
    CPU fusion workaround when opted in (module docstring)."""
    if fn is None:
        return lambda f: precision_jit(f, **jit_kwargs)
    setup_persistent_cache()
    if jax.default_backend() == "cpu":
        opts = cpu_fusion_compiler_options()
        if opts:
            jit_kwargs.setdefault("compiler_options", opts)
    return jax.jit(fn, **jit_kwargs)


# --- persistent XLA compilation cache -------------------------------------------

_cache_state = {"dir": None, "done": False}
_cache_lock = threading.Lock()


def setup_persistent_cache(force: bool = False) -> str | None:
    """Enable jax's persistent (on-disk) XLA compilation cache.

    The directory is versioned like every other pint_tpu disk cache
    (utils/cache.py): ``$PINT_TPU_CACHE_DIR/xla/jax-<version>`` — jax's own
    cache key covers program/flags/platform, the version directory guards
    against serialization-format drift across toolchains. Idempotent; call
    ``force=True`` to re-apply after changing the env knobs.

    Env: ``PINT_TPU_COMPILE_CACHE`` (the knob documented since the seed:
    a directory overrides the location, ``0`` disables — the graft entry's
    multi-device dryrun relies on the disable because XLA:CPU AOT entries
    written under different detected host features can SIGILL on load);
    ``PINT_TPU_XLA_CACHE=0`` / ``PINT_TPU_XLA_CACHE_DIR`` are equivalent
    split knobs. Cache *errors* never break a fit
    (``jax_raise_persistent_cache_errors`` is set False); a program that
    cannot be cached just compiles normally.

    Returns the cache directory in use, or None when disabled.
    """
    with _cache_lock:
        if _cache_state["done"] and not force:
            return _cache_state["dir"]
        _cache_state["done"] = True
        legacy = knobs.get("PINT_TPU_COMPILE_CACHE")
        if knobs.get("PINT_TPU_XLA_CACHE") == "0" or legacy == "0":
            _cache_state["dir"] = None
            return None
        from pint_tpu.utils.cache import cache_root

        path = knobs.get("PINT_TPU_XLA_CACHE_DIR") or legacy or str(
            cache_root() / "xla" / f"jax-{jax.__version__}"
        )
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # fit/grid programs compile in 0.5 s - minutes; cache everything
            # that costs more than a disk read
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
            jax.config.update("jax_raise_persistent_cache_errors", False)
            # jax materializes its cache object on the first compile and
            # then ignores jax_compilation_cache_dir updates: if anything
            # compiled before this ran (or a test re-points the dir), the
            # new directory only takes effect after an explicit reset
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:  # pragma: no cover — config surface drift  # jaxlint: disable=silent-except — cache-config drift just disables the compile cache; compile correctness unaffected
            _cache_state["dir"] = None
            return None
        _cache_state["dir"] = path
        return path


# --- AOT program wrapper ---------------------------------------------------------


def canonicalize_params(params):
    """Give every plain Python-float parameter leaf a concrete, strongly
    typed f64 aval.

    A Python float traces as a WEAK-typed scalar; after the first
    `apply_delta` the same leaf is a strong f64 array, which is a
    different abstract value — so the step and phase programs were being
    traced AND compiled twice per first fit (measured: the duplicate
    compile was a full second copy of the fit-step compile cost).
    Canonicalizing up front makes iteration 1 and iteration N share one
    program. Ints/bools are left alone: promoting them would change the
    program's dtype semantics."""
    import jax.numpy as jnp

    def canon(x):
        if type(x) is float:
            return jnp.asarray(x, dtype=jnp.float64)
        return x

    return jax.tree_util.tree_map(canon, params)


def _args_signature(args):
    """Hashable (treedef, leaf shapes/dtypes/weak_type) signature of a call.

    weak_type is part of a leaf's abstract value: an executable lowered
    for a strong f64 scalar rejects a weak-typed call operand, and the
    silent jit fallback then recompiles the whole program — exactly the
    overlap miss the flagship bench measured (satellite: BENCH_r05
    `fit_plus_compile_overlap_s == initial_fit_s`). Distinguishing the
    two here makes precompile signatures honest."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return treedef, tuple(
        (tuple(getattr(x, "shape", ())),
         str(getattr(x, "dtype", type(x).__name__)),
         bool(getattr(x, "weak_type", False)))
        for x in leaves
    )


class TimedProgram:
    """Jitted-callable wrapper: compile-time split + ahead-of-time compile.

    - With telemetry collecting (ops/perf.py), the first call per argument
      signature explicitly lowers+compiles under a ``compile`` stage, so
      the fit breakdown separates `fit_compile_s` from device-step time;
      execution is blocked-on so the enclosing stage measures real device
      time rather than async dispatch.
    - `precompile(*args)` compiles the executable ahead of the first call
      (safe from a worker thread — XLA compilation releases the GIL), so
      a later first call finds it ready.
    - With telemetry off and nothing precompiled, calls pass straight
      through to the jitted callable.
    - Every lowering is run through the jaxpr auditor
      (pint_tpu/analysis/jaxpr_audit.py) before it compiles:
      ``collective_axes`` declares the mesh axes whose collectives the
      program MUST contain (empty = no collective may appear, the
      1-device contract), ``canonical=True`` (the default — every fit
      program takes canonicalized operands) arms the retrace-budget
      pass, and ``precision_spec`` declares the extended-precision
      discipline (``"dd64"`` / ``"qf32"`` / ``"f64"`` or a
      :class:`~pint_tpu.analysis.ddflow.PrecisionSpec`) that arms the
      dd-flow dataflow passes — a program carrying dd operands with no
      spec draws a warn-level ``dd-spec`` audit event.
      ``PINT_TPU_AUDIT=strict`` turns violations into compile-time
      errors; ``=0`` skips the audit.
    - Each audited lowering also lands in the static cost ledger
      (pint_tpu/analysis/costmodel.py): FLOPs, bytes moved, collective
      bytes and peak live buffer bytes per program label — the numbers
      ``python -m pint_tpu.analysis.cost --check`` gates against the
      checked-in budgets.
    """

    __slots__ = ("jfn", "label", "collective_axes", "canonical",
                 "precision_spec", "_exes", "_lock")

    def __init__(self, jfn, label: str,
                 collective_axes: tuple[str, ...] = (),
                 canonical: bool = True,
                 precision_spec=None):
        self.jfn = jfn
        self.label = label
        self.collective_axes = tuple(collective_axes)
        self.canonical = canonical
        self.precision_spec = precision_spec
        self._exes: dict = {}
        self._lock = threading.Lock()

    # deepcopy-atomic, like the bare jit wrappers these replace: model
    # deepcopies share the compiled-program cache entries by reference
    # (the programs depend only on model STRUCTURE, which the copy shares)
    def __deepcopy__(self, memo):
        return self

    def __copy__(self):
        return self

    def precompile(self, *args) -> None:
        sig = _args_signature(args)
        if sig not in self._exes:
            self._compile(sig, args)

    def _compile(self, sig, args):
        """(executable, compiled_here): compiled_here is False when another
        thread's in-flight compile of the same signature was waited out —
        that wait is recorded (``compile_wait_s``) so a partially-overlapped
        background precompile shows up in the fit breakdown instead of
        hiding inside the enclosing stage."""
        import time as _time

        t0 = _time.perf_counter()
        with self._lock:
            exe = self._exes.get(sig)
            if exe is None:
                # trace (host Python, never cached) split from backend
                # compile (XLA, served from the persistent cache when warm)
                with perf.stage("trace"):
                    traced = None
                    if hasattr(self.jfn, "trace"):
                        try:
                            traced = self.jfn.trace(*args)
                        except Exception:  # pragma: no cover — stage API drift  # jaxlint: disable=silent-except — trace-API drift falls back to lower(); same program, attribution only
                            traced = None
                    lowered = (traced.lower() if traced is not None
                               else self.jfn.lower(*args))
                from pint_tpu.analysis.jaxpr_audit import audit_program

                closed = None if traced is None else traced.jaxpr
                audit_program(
                    self.label,
                    closed,
                    args,
                    collective_axes=self.collective_axes,
                    canonical=self.canonical,
                    prior_sigs=tuple(self._exes.keys()),
                    sig=sig,
                    program_id=id(self),
                    spec=self.precision_spec,
                )
                if closed is not None:
                    # static cost ledger (analysis/costmodel.py): every
                    # lowering's FLOPs/bytes land beside the audit block
                    from pint_tpu.analysis import costmodel

                    costmodel.record_program(self.label, closed)
                with perf.stage("compile"):
                    exe = lowered.compile()
                perf.add(f"compiled:{self.label}", 1)
                self._exes[sig] = exe
                return exe, True
        wait = _time.perf_counter() - t0
        if wait > 1e-3:
            perf.add("compile_wait_s", wait)
        return exe, False

    def __call__(self, *args):
        collecting = perf.active()
        if not self._exes and not collecting:
            return self.jfn(*args)
        sig = _args_signature(args)
        exe = self._exes.get(sig)
        compiled_here = False
        if exe is None:
            if not collecting:
                return self.jfn(*args)
            exe, compiled_here = self._compile(sig, args)
        try:
            out = exe(*args)
            if not compiled_here:
                # served by an executable compiled BEFORE this call
                # (precompile overlap or an earlier iteration): the
                # overlap_engaged breakdown field keys on this
                perf.add("aot_hits", 1)
        except Exception:  # jaxlint: disable=silent-except — AOT layout mismatch re-dispatches through jit — counted as aot_fallbacks telemetry
            # AOT executables are stricter than jit (layout/sharding of the
            # exact lowering); any mismatch falls back to the jit path
            perf.add("aot_fallbacks", 1)
            out = self.jfn(*args)
        if collecting:
            out = jax.block_until_ready(out)
        return out


def use_host_solve() -> bool:
    """True when the fitters' small dense linear algebra (SVD/eigh/
    Cholesky, Woodbury assembly) must run on the host / in-process CPU
    backend: non-CPU backends emulate f64 with f32 exponent RANGE, and
    factorizations of ill-conditioned matrices underflow to NaN on device
    (measured for both the WLS design-matrix SVD and the GLS red-noise
    Woodbury pieces). ``PINT_TPU_HOST_SOLVE=1`` forces it on CPU so tests
    exercise the host path."""
    return (jax.default_backend() != "cpu"
            or knobs.flag("PINT_TPU_HOST_SOLVE"))


def _tree_nbytes(obj) -> int:
    return sum(getattr(x, "nbytes", 0) for x in jax.tree_util.tree_leaves(obj))


def host_transfer(obj, device=None):
    """Move a pytree to the host/CPU device, counted and timed for the fit
    breakdown (host_transfers / host_transfer_bytes counters + the
    ``host_transfer`` stage)."""
    import numpy as np

    collecting = perf.active()
    with perf.stage("host_transfer"):
        if device is None:
            out = jax.tree_util.tree_map(np.asarray, obj)
        else:
            out = jax.device_put(obj, device)
            if collecting:
                out = jax.block_until_ready(out)
    if collecting:
        perf.add("host_transfers", 1)
        perf.add("host_transfer_bytes", _tree_nbytes(obj))
    return out


def cpu_transfer_memo():
    """Single-slot per-tag device->CPU transfer memo.

    The fitters' host-solve paths move the (large, constant-per-fit) TOA
    tensor to the CPU backend once per object rather than on every LM
    trial. The slot holds a STRONG reference to the keyed object, so
    ``is``-identity can never alias a recycled id() of a garbage-collected
    tensor (the memo outlives any one fitter — it hangs off the model's
    step-fn cache)."""
    cpu = jax.devices("cpu")[0]
    slots: dict = {}

    def put(tag, obj):
        keyed, cached = slots.get(tag, (None, None))
        if keyed is not obj:
            cached = host_transfer(obj, cpu)
            slots[tag] = (obj, cached)
        return cached

    return put


def model_cpu_memo(model):
    """One shared CPU-transfer memo per model: the GLS/wideband step and
    chi^2 closures all move the same TOA tensor, so sharing the memo
    halves the transfers. Retention is BOUNDED: one (device, CPU) tensor
    pair per tag, replaced on the next fit with a different tensor —
    weakref slots are not an option because tensor pytrees are plain
    dicts (not weakref-able)."""
    return model.__dict__.setdefault("_cpu_transfer_memo", cpu_transfer_memo())


def adaptive_fused(fused_fn, host_fn, is_good, label: str,
                   forced: bool | None = None, precompile=None):
    """Fused-device-first dispatcher with sticky host fallback.

    Calls `fused_fn` (the fully on-device program) and returns its result
    when `is_good(out)`; otherwise recomputes through `host_fn` (device
    physics + host/CPU dense solve). When the host result is good after a
    fused failure, the failure was device underflow — structural for the
    model, not the trial point — so subsequent calls skip the wasted
    device pass. On the CPU backend (PINT_TPU_HOST_SOLVE test mode) the
    host path is used unconditionally; `forced` overrides the backend
    check (tests exercise the latch logic on CPU with forced=False).

    The returned callable carries its dispatch telemetry as attributes —
    ``solve_path`` ("fused" | "host", the sticky outcome), ``last_path``
    (the path the most recent call used) and ``latch_reason`` (why the
    host path latched) — and latches the same into any collecting perf
    report. `precompile`, when given, is exposed as ``call.precompile``
    so fitter-level AOT warmup reaches the right underlying programs.
    """
    if forced is None:
        forced = jax.default_backend() == "cpu"
    state = {"skip_fused": False, "reason": "forced_host" if forced else None}

    def _note(path):
        # refresh the callable's telemetry attributes + latch into any
        # collecting perf report
        call.last_path = path
        call.solve_path = "host" if (forced or state["skip_fused"]) else "fused"
        call.latch_reason = state["reason"]
        perf.put("solve_path", call.solve_path)
        perf.put("solve_path_reason", state["reason"])

    def call(*args):
        if not forced and not state["skip_fused"]:
            out = fused_fn(*args)
            # fault-injection site: tier-1 drives the sticky fallback on
            # any backend by NaN-poisoning the fused program's output
            from pint_tpu.testing import faults

            out = faults.poison_nonfinite("fit.step", out, label)
            if is_good(out):
                _note("fused")
                return out
            host_out = host_fn(*args)
            from pint_tpu.ops import degrade

            if is_good(host_out):
                state["skip_fused"] = True
                state["reason"] = "device_nonfinite_host_clean"
                degrade.record(
                    "fit.host_fallback", label,
                    "on-device result non-finite but host result clean "
                    "(device underflow) — using the host path from now on",
                    bound_us=0.0,  # accuracy preserved; throughput degraded
                    fix="condition the model (fewer degenerate params) or "
                        "run the solve on a true-f64 backend",
                )
            else:
                # NOT a degradation: both paths agree the trial point is
                # bad; run_lm's backtracking handles it (no ledger write)
                state["reason"] = "both_paths_nonfinite"
            _note("host")
            return host_out
        _note("host")
        return host_fn(*args)

    call.state = state
    call.last_path = None
    call.solve_path = "host" if forced else "fused"
    call.latch_reason = state["reason"]
    if precompile is not None:
        call.precompile = precompile
    return call
