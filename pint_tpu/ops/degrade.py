"""Degradation ledger: every graceful-degradation decision, on the record.

The paper's headline claim is Tempo/Tempo2 parity at the ~10 ns level,
yet several paths degrade far past that while only emitting a log line:
zero clock corrections when no clock files are discoverable (worth ~µs,
astro/clock.py), a stale clock cache served because every mirror failed
(astro/global_clock.py), zero EOP outside the IERS table (astro/eop.py),
the analytic ephemeris standing in for a requested JPL DE kernel
(astro/ephemeris.py), and the sticky host fallback when a fused device
program goes non-finite (ops/compile.py::adaptive_fused,
fitting/sharded.py::run_fused_fit). A production fit must carry a
machine-readable record of every corner it cut; a pipeline operator must
be able to turn "degrade silently" into "fail loudly".

This module is that record — the degradation counterpart of the PR-3
audit ledger (analysis/jaxpr_audit.py):

- Call sites report through :func:`record`, passing a ``kind`` from the
  registered :data:`KINDS` taxonomy (unregistered kinds raise — a typo'd
  kind is a dead ledger entry nobody can alert on), the affected
  component, a conservative quantified timing-error bound in µs where
  one is statable, and the knob that would fix the degradation.
- Events accumulate in a process-global ledger; repeated identical
  degradations (same kind + component) bump a count instead of spamming
  — the warning is emitted once, like utils.logging.log_once.
- :func:`degradation_block` snapshots the ledger for ``FitResult.perf``
  (the ``degradations`` block, ops/perf.py), ``Residuals.degradations``,
  and both smoke-bench headlines (bench.py ``degradation_count``).
- ``PINT_TPU_DEGRADED`` escalates: ``warn`` (default — log once and
  record), ``error`` (raise :class:`DegradedError` at the moment of the
  ledger write — production mode; the event is recorded first so the
  ledger still shows WHAT refused), ``0`` (record silently).

Every degradation kind is driven end-to-end by an injected fault in
tier-1 (tests/test_degrade.py, pint_tpu/testing/faults.py) and asserted
to both recover and write the right ledger event.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.degrade")

__all__ = [
    "KINDS", "DegradedError", "DegradationEvent", "add_observer",
    "degradation_block", "degradation_count", "events", "mode", "record",
    "remove_observer", "reset_ledger",
]

#: the degradation taxonomy: kind -> one-line description. A ledger write
#: with a kind outside this table raises ValueError at the call site —
#: the taxonomy is the contract tier-1 fault-injection enumerates.
KINDS: dict[str, str] = {
    "clock.zero_corrections": (
        "no clock files discoverable for an observatory; corrections are zero"),
    "clock.stale_cache": (
        "every clock-repository mirror failed; serving the stale cached copy"),
    "clock.beyond_table": (
        "TOAs beyond a clock file's last entry; holding the last correction"),
    "eop.outside_table": (
        "epochs outside the configured EOP table; UT1=UTC / zero polar motion"),
    "ephemeris.analytic_fallback": (
        "a JPL DE kernel was requested/configured but the analytic ephemeris served"),
    "fit.host_fallback": (
        "a fused device fit program went non-finite; recomputed on the host"),
    "fit.incremental_fallback": (
        "an incremental append refit left its staleness envelope; the full "
        "warm refit ran instead"),
    "fit.aot_layout_fallback": (
        "an AOT/deserialized executable rejected its call operands "
        "(layout/sharding mismatch); the signature re-dispatches through "
        "jit, latched sticky"),
    "serve.shed": (
        "serving admission control refused or dropped a request under "
        "overload (queue depth / per-tenant rate); the client saw an "
        "explicit shed, not a collapsed tail latency"),
    "serve.evict": (
        "a warm resident session was evicted from the serving pool "
        "(LRU under PINT_TPU_SERVE_POOL_SESSIONS); its next request "
        "pays a checkpoint restore instead of a millisecond append"),
    "serve.deadline": (
        "a queued request passed its deadline and was shed instead of "
        "occupying a dispatch slot (submit deadline_s / "
        "PINT_TPU_SERVE_DEADLINE_MS)"),
    "serve.retry": (
        "a serving dispatch failed transiently and was retried with "
        "backoff (PINT_TPU_SERVE_RETRIES); latency lost, no wrong answer"),
    "serve.quarantine": (
        "a hung or crash-looping serving lane was quarantined (watchdog "
        "/ consecutive-failure threshold); its session stops serving "
        "while the rest of the fleet continues"),
    "serve.journal_truncated": (
        "the write-ahead request journal ended in a torn record (a "
        "process died mid-write); recovery kept every whole record and "
        "truncated the tail"),
    "serve.journal_corrupt": (
        "a journal segment or fleet checkpoint failed its checksum and "
        "was quarantined beside the store; the records past the "
        "corruption were NOT replayed"),
    "serve.migrate": (
        "a live session was migrated between serving replicas "
        "(checkpoint + journal-suffix handoff with idempotency dedup, "
        "serve/migrate.py); the session paused for the handoff, no "
        "request was lost"),
    "serve.replica_lost": (
        "a serving replica died or stopped answering; the survivors "
        "absorbed its sessions from its durable checkpoints + journal "
        "suffix (serve/fleet.py absorb) and kept serving"),
    "serve.journal_full": (
        "the write-ahead journal hit ENOSPC on an append/fsync; the "
        "write was shed with an explicit refusal (JournalError -> 503 "
        "at the gateway) while reads and already-admitted work "
        "continue — an acked request is never silently undurable"),
    "campaign.resumed": (
        "a campaign resumed from its durable unit checkpoints after a "
        "preemption/kill; completed units were skipped and the "
        "remainder re-ran from their content-keyed seeds, so the "
        "assembled result is bitwise-identical to an uninterrupted run"),
    "campaign.checkpoint_corrupt": (
        "a campaign unit result or progress snapshot failed its crc32 "
        "and was quarantined beside the store; the unit re-runs from "
        "its seed (or an older snapshot generation serves) instead of "
        "restoring garbage"),
    "fetch.mirror_failed": (
        "a remote file could not be refreshed from any mirror"),
    "fetch.corrupt_quarantined": (
        "a downloaded file failed validation and was quarantined"),
    "obs.zero_velocity": (
        "spacecraft TOAs without velocity flags; zero GCRS velocity assumed"),
}


class DegradedError(RuntimeError):
    """A graceful degradation under PINT_TPU_DEGRADED=error."""


class DegradationEvent(NamedTuple):
    kind: str
    component: str
    detail: str
    #: conservative timing-error bound in µs, when one is statable
    bound_us: float | None
    #: the knob/config that would fix the degradation
    fix: str | None
    count: int = 1
    #: monotonic clock of the LATEST occurrence (time.monotonic —
    #: orderable against trace spans and flight-recorder events)
    t_mono: float | None = None
    #: the active request trace id at the latest occurrence, when the
    #: degradation fired inside a traced request (pint_tpu/obs/trace.py)
    #: — serve.shed/serve.evict/fit.host_fallback events are joinable
    #: against the trace buffer
    trace_id: str | None = None


def mode() -> str:
    """"warn" | "error" | "0" (PINT_TPU_DEGRADED, defaulting to warn)."""
    m = (knobs.get("PINT_TPU_DEGRADED") or "warn").lower()
    return m if m in ("warn", "error", "0") else "warn"


_lock = threading.Lock()
#: (kind, component) -> DegradationEvent (count bumped on repeats)
_events: dict[tuple[str, str], DegradationEvent] = {}
#: ledger observers, called with every (merged) event AFTER the ledger
#: write and BEFORE any =error escalation — the flight recorder and the
#: metrics registry subscribe here, so a refused degradation is still
#: on every observability surface
_observers: list = []


def add_observer(fn) -> None:
    """Subscribe ``fn(event)`` to every ledger write (idempotent)."""
    if fn not in _observers:
        _observers.append(fn)


def remove_observer(fn) -> None:
    if fn in _observers:
        _observers.remove(fn)


def reset_ledger() -> None:
    """Forget every recorded degradation (test isolation)."""
    with _lock:
        _events.clear()


def record(kind: str, component: str, detail: str = "",
           bound_us: float | None = None, fix: str | None = None) -> bool:
    """Record one graceful-degradation decision; escalate per the knob.

    Returns True when this is the FIRST occurrence of (kind, component)
    — callers use it to gate any extra side effects (the warning itself
    is emitted here, once). Under ``PINT_TPU_DEGRADED=error`` the event
    is recorded and then :class:`DegradedError` raises, so a production
    pipeline refuses the corner-cut while the ledger still shows it.
    """
    if kind not in KINDS:
        raise ValueError(
            f"{kind!r} is not a registered degradation kind; add it to "
            "pint_tpu.ops.degrade.KINDS so the taxonomy stays complete "
            f"(known: {sorted(KINDS)})"
        )
    # joinability: every event is stamped with a monotonic clock and,
    # when it fires inside a traced request, the active trace id — a
    # serve.shed/serve.evict/fit.host_fallback on the ledger points at
    # the exact request trace that suffered it
    t_mono = time.monotonic()
    try:
        from pint_tpu.obs import trace as _trace

        trace_id = _trace.current_trace_id()
    except ImportError:  # pragma: no cover — obs layer absent mid-bootstrap  # jaxlint: disable=silent-except — tracing is optional context; the ledger write itself must never fail
        trace_id = None
    key = (kind, component)
    with _lock:
        prior = _events.get(key)
        if prior is not None:
            _events[key] = merged = prior._replace(
                count=prior.count + 1, t_mono=t_mono,
                trace_id=trace_id or prior.trace_id)
            first = False
        else:
            _events[key] = merged = DegradationEvent(
                kind, component, detail, bound_us, fix,
                t_mono=t_mono, trace_id=trace_id)
            first = True
    for obs in list(_observers):
        try:
            obs(merged)
        except Exception as e:  # noqa: BLE001  # jaxlint: disable=silent-except — an observer failure must never break the ledger write it observes; logged once per message by the dedup filter
            log.error(f"degradation observer {obs!r} failed: {e}")
    m = mode()
    msg = f"degraded [{kind}] {component}: {detail}"
    if bound_us is not None:
        msg += f" (timing-error bound ~{bound_us:g} us)"
    if fix:
        msg += f" — fix: {fix}"
    if m == "error":
        raise DegradedError(
            msg + " [raised because PINT_TPU_DEGRADED=error]")
    if m == "warn" and first:
        log.warning(msg)
    return first


def events() -> list[DegradationEvent]:
    """Snapshot of the recorded events (insertion order)."""
    with _lock:
        return list(_events.values())


def degradation_count() -> int:
    """Distinct (kind, component) degradations recorded so far."""
    with _lock:
        return len(_events)


def degradation_block(max_events: int = 20) -> dict:
    """JSON-ready ledger snapshot: the ``degradations`` block attached to
    ``FitResult.perf``, ``Residuals.degradations`` and both smoke-bench
    headline records."""
    evs = events()
    return {
        "n_events": len(evs),
        "kinds": sorted({e.kind for e in evs}),
        "events": [
            {"kind": e.kind, "component": e.component, "detail": e.detail,
             "bound_us": e.bound_us, "fix": e.fix, "count": e.count,
             "t_mono": e.t_mono, "trace": e.trace_id}
            for e in evs[:max_events]
        ],
        "mode": mode(),
    }


if __name__ == "__main__":  # pragma: no cover — tiny smoke entry
    import json

    print(json.dumps(degradation_block(), indent=2))
