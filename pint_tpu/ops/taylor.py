"""Horner evaluation of factorial-scaled Taylor series.

``taylor_horner(x, [c0, c1, c2, c3])`` = c0 + c1 x + c2 x^2/2! + c3 x^3/3!.

This is the spindown-phase kernel (the reference's longdouble
`pint.utils.taylor_horner`, utils.py:355 — its single hottest numerical
convention). Here the precision-critical variant runs in double-double: the
spin frequency term F0*dt with dt ~ 1e9 s and F0 ~ 1e2-1e3 Hz produces ~1e11
turns that must stay exact to ~1e-9 turns.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax.numpy as jnp

from pint_tpu.ops.dd import DD, dd, dd_add, dd_add_fp, dd_mul, dd_mul_fp

Array = jnp.ndarray

_FACT = [1.0]
for _i in range(1, 40):
    _FACT.append(_FACT[-1] * _i)


def taylor_horner(x: Array, coeffs: Sequence[Array]) -> Array:
    """float64 Horner sum_i coeffs[i] * x^i / i! (for derivatives and
    non-critical series)."""
    if len(coeffs) == 0:
        return jnp.zeros_like(x)
    acc = jnp.asarray(coeffs[-1], jnp.float64) / _FACT[len(coeffs) - 1]
    for i in range(len(coeffs) - 2, -1, -1):
        acc = acc * x + jnp.asarray(coeffs[i], jnp.float64) / _FACT[i]
    return jnp.broadcast_to(acc, jnp.shape(x))


def taylor_horner_deriv(x: Array, coeffs: Sequence[Array], deriv_order: int = 1) -> Array:
    """d^n/dx^n of taylor_horner (reference: utils.py:382). The factorial
    scaling makes this a simple coefficient shift."""
    if deriv_order == 0:
        return taylor_horner(x, coeffs)
    shifted = list(coeffs[deriv_order:])
    if not shifted:
        return jnp.zeros_like(x)
    return taylor_horner(x, shifted)


def taylor_horner_x(xp, x, coeffs: Sequence) -> object:
    """Backend-generic Horner: x and result in xp's extended precision;
    coefficients may be backend leaves (DD/QF) or plain f64."""
    if len(coeffs) == 0:
        return xp.zeros_like(x[0] if hasattr(x, "__getitem__") else x)
    acc = xp.mul_f(xp.lift(coeffs[-1]), 1.0 / _FACT[len(coeffs) - 1])
    for i in range(len(coeffs) - 2, -1, -1):
        acc = xp.mul(acc, x)
        acc = xp.add(acc, xp.mul_f(xp.lift(coeffs[i]), 1.0 / _FACT[i]))
    return acc


def taylor_horner_dd(x: DD, coeffs: Sequence[Union[Array, DD]]) -> DD:
    """Double-double Horner: x is DD, coefficients float64 (or DD).

    Each step is acc = acc*x + c_i/i!, fully in dd arithmetic. The factorial
    division happens in float64 (coefficients are model parameters known to
    float64 anyway; the *accumulation* is what needs dd).
    """
    if len(coeffs) == 0:
        return dd(jnp.zeros_like(x.hi))  # jaxlint: disable=dd-truncate — shape/dtype metadata only, no value read
    last = coeffs[-1]
    if isinstance(last, DD):
        acc = dd_mul_fp(last, 1.0 / _FACT[len(coeffs) - 1])
    else:
        acc = dd(jnp.asarray(last, jnp.float64) / _FACT[len(coeffs) - 1])
    for i in range(len(coeffs) - 2, -1, -1):
        acc = dd_mul(acc, x)
        c = coeffs[i]
        if isinstance(c, DD):
            acc = dd_add(acc, dd_mul_fp(c, 1.0 / _FACT[i]))
        else:
            acc = dd_add_fp(acc, jnp.asarray(c, jnp.float64) / _FACT[i])
    return acc
