"""Crash-safe fleet recovery: checkpoints + journal replay, cross-process.

The durability contract (ISSUE 14): a serving process that dies —
killed mid-dispatch, OOMed, power-cycled — loses NO admitted work. The
pieces it leaves behind are all durable, content-addressed artifacts:

- ``<dir>/sessions/<sid>.ckpt`` — per-session fleet checkpoints
  (:func:`checkpoint_fleet`): the pickled
  :class:`~pint_tpu.serve.pool.SessionCheckpoint` (model + raw TOA rows
  + exact ``FitterState`` solution + the idempotency keys already
  applied), framed with a crc32 so a corrupt file is quarantined, never
  silently restored;
- ``<dir>/journal/`` — the write-ahead request journal
  (serve/journal.py): every request admitted after the last checkpoint;
- the ``.aotx`` serialized-executable store + prepared-TOA disk cache +
  persistent XLA cache (shared ``PINT_TPU_CACHE_DIR``) — so the restored
  fleet's programs deserialize instead of retracing.

:func:`recover_fleet` reassembles a live :class:`ServingEngine` from
them in a FRESH process: restore every checkpoint (zero traces under
``PINT_TPU_EXPECT_WARM=1`` in a warmed environment), replay the journal
suffix with idempotency-key dedup (a request that was journaled AND
already applied in the checkpoint is skipped, so crash-then-recover
never double-appends), and report ``requests_lost`` (must be 0),
``recovery_time_s`` and ``journal_replay_reqs_per_sec``. The replay and
restore walls land in the ``serve_breakdown`` perf stages (``recover`` /
``replay``), so the ≥90% serve-attribution contract covers recovery.

The CLI leg is ``pint_tpu recover --dir <dir>`` (scripts/recover.py);
the kill-mid-trace drill in tier-1 (tests/test_recover.py) proves a
killed process's twin recovers with results ≡ the never-crashed fleet.
"""

from __future__ import annotations

import os
import pickle
import shutil
import struct
import time
import zlib
from pathlib import Path

from pint_tpu.ops import degrade, perf
from pint_tpu.testing import faults
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["checkpoint_fleet", "load_fleet_checkpoints", "recover_fleet"]

_FRAME = struct.Struct("<II")          # payload length, crc32(payload)


def _session_dir(dirpath: Path) -> Path:
    return Path(dirpath) / "sessions"


def _write_checkpoint(path: Path, ck) -> None:
    """crc-framed atomic checkpoint write — shared by the fleet
    ``SessionCheckpoint`` store and the campaign unit-result/snapshot
    stores (pint_tpu/campaign/runner.py). The ``campaign.checkpoint``
    fault site drills both: ``kill`` dies mid-write with a torn ``.tmp``
    on disk (the previous generation behind the atomic rename must stay
    intact and loadable), ``corrupt`` bit-flips the payload under a
    valid-looking frame (the read path must quarantine, never restore
    garbage)."""
    payload = pickle.dumps(ck, protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path.with_suffix(".tmp")
    mode = faults.trip("campaign.checkpoint", path.name)
    if mode == "corrupt":
        # the frame promises the original crc but the payload lies —
        # only the read path (crc validation) can catch it
        frame = _FRAME.pack(len(payload), zlib.crc32(payload))
        payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
    else:
        frame = _FRAME.pack(len(payload), zlib.crc32(payload))
    with open(tmp, "wb") as fh:
        fh.write(frame)
        if mode == "kill":
            # the kill-mid-write drill: half the payload reaches disk,
            # then the process dies — the torn .tmp is never renamed,
            # so the previous checkpoint generation stays intact
            fh.write(payload[: max(len(payload) // 2, 1)])
            fh.flush()
            os.fsync(fh.fileno())
            os._exit(70)
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())
    tmp.replace(path)                  # atomic: never a half checkpoint


def _read_checkpoint(path: Path):
    data = path.read_bytes()
    if len(data) < _FRAME.size:
        raise ValueError("checkpoint shorter than its frame header")
    length, crc = _FRAME.unpack_from(data, 0)
    payload = data[_FRAME.size: _FRAME.size + length]
    if len(payload) < length or zlib.crc32(payload) != crc:
        raise ValueError("checkpoint failed its crc32")
    return pickle.loads(payload)


def checkpoint_fleet(pool, dirpath: str | Path, journal=None) -> list[str]:
    """Durably checkpoint EVERY pooled session (live ones are captured
    non-destructively — they stay live) into ``<dir>/sessions/``, then
    pin the journal's compaction boundary to it: records covered by the
    checkpoints are deleted and each session's applied-idempotency-key
    set restarts empty (those keys can never be replayed again).
    Returns the checkpointed sids.

    Call at a quiesced boundary — between worker turns, or while the
    engine is draining (``ServingEngine.stop(drain=True)`` does): a
    request admitted between a session's capture and the compaction
    marker would have its journal record compacted away before its
    effect reaches any checkpoint."""
    from pint_tpu.serve.pool import SessionCheckpoint

    sdir = _session_dir(Path(dirpath))
    sdir.mkdir(parents=True, exist_ok=True)
    sids = []
    with perf.stage("serve"), perf.stage("checkpoint"), pool._lock:
        for sid in pool.sids():
            ses = pool._live.get(sid)
            ck = (SessionCheckpoint.capture(ses) if ses is not None
                  else pool._checkpoints[sid])
            _write_checkpoint(sdir / f"{sid}.ckpt", ck)
            sids.append(sid)
        if journal is not None:
            journal.mark_checkpoint(sids)
            # the compacted records are gone: their idempotency keys are
            # unreachable by any future replay, so the per-session sets
            # (bounded memory) restart at the checkpoint boundary
            for sid in sids:
                ses = pool._live.get(sid)
                if ses is not None:
                    ses.applied_idem.clear()
    perf.add("serve_checkpoints", len(sids))
    return sids


def load_fleet_checkpoints(dirpath: str | Path) -> dict:
    """Read every session checkpoint under ``<dir>/sessions/``; a file
    that fails its crc (or does not unpickle) is quarantined beside the
    store with ``serve.journal_corrupt`` on the ledger — a lying
    checkpoint must refuse loudly (``PINT_TPU_DEGRADED=error``), never
    restore garbage."""
    sdir = _session_dir(Path(dirpath))
    out = {}
    for path in sorted(sdir.glob("*.ckpt")):
        try:
            out[path.stem] = _read_checkpoint(path)
        except Exception as e:  # noqa: BLE001 — quarantined + ledgered below, never silent  # jaxlint: disable=silent-except
            qdir = sdir / "quarantine"
            qdir.mkdir(parents=True, exist_ok=True)
            shutil.copy2(path, qdir / path.name)
            degrade.record(
                "serve.journal_corrupt", path.name,
                f"fleet checkpoint failed validation ({e}); preserved at "
                f"{qdir / path.name}, session NOT restored",
                fix="restore the session from an older checkpoint or "
                    "refit it from its TOAs, then re-checkpoint")
    return out


def recover_fleet(dirpath: str | Path, *, replay: bool = True,
                  engine_kwargs: dict | None = None):
    """Rebuild a live, journaled :class:`ServingEngine` from a durable
    serving directory in THIS (fresh) process.

    Restores every session checkpoint into a warm pool, replays the
    journal suffix with idempotency-key dedup, and reopens the journal
    for continued service. Returns ``(engine, report)``; the engine is
    NOT started (call ``engine.start()`` — the CLI leg does).

    ``report["requests_lost"]`` counts journaled requests that could be
    neither applied nor deduped; the durability contract (and the tier-1
    kill drill) locks it at 0.
    """
    from pint_tpu.serve.engine import ServingEngine
    from pint_tpu.serve.journal import decode_rows, replay_records
    from pint_tpu.serve.pool import SessionPool

    dirpath = Path(dirpath)
    t0 = time.perf_counter()
    kw = dict(engine_kwargs or {})
    with perf.stage("serve"), perf.stage("recover"):
        checkpoints = load_fleet_checkpoints(dirpath)
        pool = SessionPool(capacity=max(len(checkpoints) + 1,
                                        kw.pop("pool_capacity", 0) or 0))
        for sid, ck in checkpoints.items():
            pool.put(sid, ck.restore())
            pool.restores += 1
        records, jreport = replay_records(dirpath / "journal")
    restore_s = time.perf_counter() - t0

    engine = ServingEngine(pool, durable_dir=dirpath, **kw)
    replayed = deduped = lost = 0
    t1 = time.perf_counter()
    if replay and not jreport["clean_close"]:
        # live-migration ownership markers (serve/migrate.py): a
        # ``migrate_out`` voids the session's EARLIER records — they
        # moved with it, another replica owns them now — unless a later
        # ``migrate_in`` handed the session back. Pre-scan for each
        # session's last ownership transfer, then skip request records
        # it covers.
        moved_out_at: dict = {}
        for rec in records:
            if rec.get("op") == "migrate_out":
                moved_out_at[rec.get("sid")] = rec.get("seq", 0)
            elif rec.get("op") == "migrate_in":
                moved_out_at.pop(rec.get("sid"), None)
        with perf.stage("serve"), perf.stage("replay"):
            for rec in records:
                if rec.get("op") != "request":
                    continue
                sid = rec["session"]
                if rec.get("seq", 0) < moved_out_at.get(sid, -1):
                    continue           # moved with the session, not lost
                if sid not in pool:
                    lost += 1
                    log.error(f"journal record seq {rec['seq']} names "
                              f"unknown session {sid!r}; request LOST")
                    continue
                ses = pool.get(sid)
                if rec.get("idem") in ses.applied_idem:
                    deduped += 1     # already inside the checkpoint
                    continue
                # accepted work is data: replay applies it directly on
                # the session (admission/deadline govern live clients,
                # not recovery — the client that was acked is gone, the
                # TOAs it delivered must not be)
                if rec["kind"] == "append":
                    ses.append(**decode_rows(rec["rows"]))
                else:
                    from pint_tpu.serve.session import batch_refit

                    batch_refit([ses], maxiter=engine.maxiter)
                if rec.get("idem"):
                    ses.applied_idem.add(rec["idem"])
                replayed += 1
    replay_s = time.perf_counter() - t1
    recovery_s = time.perf_counter() - t0

    report = {
        "dir": str(dirpath),
        "sessions": len(checkpoints),
        "clean_close": jreport["clean_close"],
        "journal_records": len(records),
        "replayed": replayed,
        "deduped": deduped,
        "requests_lost": lost,
        "truncated_records": jreport["truncated_records"],
        "corrupt_segments": jreport["corrupt_segments"],
        "restore_s": round(restore_s, 4),
        "replay_s": round(replay_s, 4),
        "recovery_time_s": round(recovery_s, 4),
        "journal_replay_reqs_per_sec": (
            round(replayed / replay_s, 3) if replayed and replay_s > 0
            else None),
    }
    from pint_tpu.obs import flight

    flight.note("recover", dir=str(dirpath), sessions=len(checkpoints),
                replayed=replayed, deduped=deduped, lost=lost)
    log.info(f"recovered fleet from {dirpath}: {len(checkpoints)} "
             f"session(s), {replayed} replayed, {deduped} deduped, "
             f"{lost} lost in {recovery_s:.2f}s")
    return engine, report
