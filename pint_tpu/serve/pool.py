"""Warm session pool: LRU-resident timing sessions with checkpoint/restore.

A resident :class:`~pint_tpu.serve.session.TimingSession` is what makes
appends O(k): prepared columns, a built tensor, cached normal-equation
blocks, warm program handles. It is also what bounds fleet size — a
process cannot keep every pulsar's session hot. This pool keeps the
``PINT_TPU_SERVE_POOL_SESSIONS`` most-recently-used sessions live and
turns the rest into cheap checkpoints:

- **Eviction** captures a :class:`SessionCheckpoint` — the fitted
  solution as a :class:`~pint_tpu.fitting.state.FitterState` snapshot
  (exact (hi, lo) parameter pairs) plus the RAW TOA inputs (epochs /
  errors / frequencies / observatories / flags — a handful of scalars
  per TOA, not the ~30-column prepared set) — then drops the live
  session. Every eviction is a ledger-visible ``serve.evict``
  degradation (ops/degrade.py): refusable under
  ``PINT_TPU_DEGRADED=error``, observable in the bench headline.
- **Restore** re-prepares the TOAs through the content-hash prepared-
  column disk cache (sets stored by ``TOAs.append`` are direct hits),
  rebuilds the fitter, warm-starts it from the snapshot and recaptures
  the incremental blocks at that exact point
  (:meth:`TimingSession.from_state`). Every program this touches is
  served by the process-global program caches or the ``.aotx``
  serialized-executable store — an evicted-then-restored session
  answers its next append with ZERO traces under
  ``PINT_TPU_EXPECT_WARM=1`` (locked by tests/test_serve.py), and its
  answer is the never-evicted session's answer to ≤1e-10.

The ``serve.pool:evict`` fault site (testing/faults.py) forces an
eviction on the next :meth:`SessionPool.get`, so the restore path is
drillable end-to-end via ``PINT_TPU_FAULTS`` without memory pressure.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from pint_tpu.obs import flight
from pint_tpu.ops import degrade, perf
from pint_tpu.serve.session import TimingSession
from pint_tpu.testing import faults
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["SessionCheckpoint", "SessionPool"]


@dataclass
class SessionCheckpoint:
    """Everything needed to rebuild a resident session without its live
    device state: the model object (program caches key on it), the raw
    TOA inputs, the fitted solution, and the session's serving config."""

    model: object
    state: object                  # fitting.state.FitterState
    utc: object                    # astro.time.MJDEpoch of every row
    error_us: np.ndarray
    freq_mhz: np.ndarray
    obs: np.ndarray
    flags: list
    n_toas: int
    maxiter: int
    required_chi2_decrease: float
    max_rejects: int
    #: idempotency keys already applied at capture time — journal replay
    #: (serve/recover.py) dedups against this, so a request that landed
    #: in the checkpoint AND survives in the journal is never re-applied
    applied_idem: list = field(default_factory=list)

    @classmethod
    def capture(cls, session: TimingSession) -> "SessionCheckpoint":
        from pint_tpu.fitting.state import snapshot

        toas = session.toas
        if getattr(toas, "utc_raw", None) is None:
            raise ValueError(
                "session TOAs carry no raw UTC epochs; cannot checkpoint")
        return cls(
            model=session.model,
            state=snapshot(session.fitter),
            utc=toas.utc_raw,
            error_us=np.asarray(toas.error_us),
            freq_mhz=np.asarray(toas.freq_mhz),
            obs=np.asarray(toas.obs),
            flags=[dict(f) for f in toas.flags],
            n_toas=len(toas),
            maxiter=session.maxiter,
            required_chi2_decrease=session.required_chi2_decrease,
            max_rejects=session.max_rejects,
            applied_idem=sorted(getattr(session, "applied_idem", ())),
        )

    def restore(self) -> TimingSession:
        """Rebuild the live session at the checkpointed solution. The
        prepared columns come back through the content-hash disk cache
        when available (an appended session stored its merged set under
        its full key), a plain host re-prepare otherwise — either way no
        program traces: the blocks/chi² programs the restored engine
        re-warms are process-cache or ``.aotx`` hits."""
        from pint_tpu.toas import prepare_arrays

        toas = prepare_arrays(self.utc, self.error_us, self.freq_mhz,
                              self.obs, flags=self.flags, cache=True)
        ses = TimingSession.from_state(
            toas, self.model, self.state, maxiter=self.maxiter,
            required_chi2_decrease=self.required_chi2_decrease,
            max_rejects=self.max_rejects)
        ses.applied_idem = set(self.applied_idem)
        return ses


class SessionPool:
    """LRU-bounded warm sessions, evicting to checkpoints (see module
    docstring). ``capacity`` defaults to ``PINT_TPU_SERVE_POOL_SESSIONS``."""

    def __init__(self, capacity: int | None = None):
        self.capacity = int(knobs.get("PINT_TPU_SERVE_POOL_SESSIONS")) \
            if capacity is None else int(capacity)
        if self.capacity < 1:
            raise ValueError("session pool capacity must be >= 1")
        self._live: OrderedDict[str, TimingSession] = OrderedDict()
        self._checkpoints: dict[str, SessionCheckpoint] = {}
        # guards the LRU bookkeeping: the serving worker, a watchdog
        # replacement worker and client submit threads can all touch the
        # pool concurrently (an OrderedDict mutated from two threads
        # corrupts); the session OBJECTS stay single-dispatcher
        self._lock = threading.RLock()
        # per-session restore/evict mutexes (see session_lock): held
        # across a restore (which runs OUTSIDE the global lock) and by
        # the dispatcher while it mutates the session, so an eviction
        # can never capture a checkpoint of a session mid-restore or
        # mid-append — eviction try-acquires and skips a busy session
        self._sess_locks: dict[str, threading.RLock] = {}
        self.hits = 0
        self.evictions = 0
        self.restores = 0
        self.restore_s = 0.0

    def __len__(self) -> int:
        with self._lock:
            return len(self._live)

    def __contains__(self, sid: str) -> bool:
        with self._lock:
            return sid in self._live or sid in self._checkpoints

    def sids(self) -> list[str]:
        """Every registered session id (live + checkpointed)."""
        with self._lock:
            return list(self._live) + [s for s in self._checkpoints
                                       if s not in self._live]

    def session_lock(self, sid: str) -> threading.RLock:
        """The per-session restore/evict mutex for ``sid`` (created on
        first use, reentrant). Held by :meth:`get` across a checkpoint
        restore and by the serving dispatcher while it mutates the
        session, so a concurrent eviction (which try-acquires it) can
        never capture a checkpoint of a half-restored or half-appended
        session — the race a watchdog replacement worker used to lose."""
        with self._lock:
            lk = self._sess_locks.get(sid)
            if lk is None:
                lk = self._sess_locks[sid] = threading.RLock()
            return lk

    def _evict(self, sid: str) -> bool:
        """Capture + drop ``sid`` (caller holds the global lock). The
        per-session mutex is try-acquired: a session pinned by a
        concurrent restore or an in-flight dispatch is NOT evictable —
        returns False and the caller picks another victim — because a
        checkpoint captured mid-mutation would lose the mutation."""
        lk = self._sess_locks.get(sid)
        if lk is None:
            lk = self._sess_locks[sid] = threading.RLock()
        if not lk.acquire(blocking=False):
            return False
        try:
            session = self._live.pop(sid)
            self._checkpoints[sid] = SessionCheckpoint.capture(session)
            self.evictions += 1
            perf.add("serve_evictions")
            degrade.record(
                "serve.evict", f"session:{sid}",
                f"warm session {sid!r} evicted at pool capacity "
                f"{self.capacity}; next request pays a checkpoint restore",
                bound_us=0.0,  # accuracy preserved; the restore latency lost
                fix="raise PINT_TPU_SERVE_POOL_SESSIONS or shard the fleet "
                    "across more processes")
        finally:
            lk.release()
        return True

    def put(self, sid: str, session: TimingSession) -> None:
        """Register (or re-insert) a live session; evicts the LRU
        session past capacity. Under ``PINT_TPU_DEGRADED=error`` the
        eviction's ledger write raises BEFORE the new session is
        inserted — an overfull pool refuses instead of silently churning
        its warm set."""
        with self._lock:
            if sid in self._live:
                self._live.move_to_end(sid)
                self._live[sid] = session
                return
            while len(self._live) >= self.capacity:
                # the ledger write (and any PINT_TPU_DEGRADED=error
                # raise) happens inside _evict, checkpoint captured
                # first; a victim pinned by a concurrent restore/
                # dispatch is skipped (evicting it would capture a
                # half-mutated session)
                if not any(self._evict(cand) for cand in list(self._live)):
                    log.warning(
                        f"session pool over capacity ({len(self._live)} "
                        f">= {self.capacity}) with every victim pinned "
                        "by a concurrent restore/dispatch; admitting "
                        f"{sid!r} over capacity")
                    break
            self._live[sid] = session
            self._checkpoints.pop(sid, None)

    def remove(self, sid: str) -> None:
        """Forget ``sid`` entirely — live session and checkpoint. The
        migration export path (serve/migrate.py) calls this after the
        handoff checkpoint is written: the source replica no longer
        owns the session. Unknown sids are a no-op."""
        with self.session_lock(sid):
            with self._lock:
                self._live.pop(sid, None)
                self._checkpoints.pop(sid, None)

    def get(self, sid: str) -> TimingSession:
        """The live session for ``sid``, restoring from its checkpoint
        when evicted. Unknown sids raise KeyError. The restore runs
        under the per-session mutex but OUTSIDE the global lock: a
        multi-second re-prepare must not block the whole pool, and two
        threads racing for the same evicted session restore it once
        (the loser blocks, then takes the warm fast path)."""
        with self.session_lock(sid):
            with self._lock:
                if (sid in self._live
                        and faults.trip("serve.pool",
                                        f"session:{sid}") is not None):
                    # fault drill: evict the requested session so THIS
                    # request pays the restore path
                    # (PINT_TPU_FAULTS=serve.pool:evict); the acquire
                    # inside _evict is reentrant — same thread
                    self._evict(sid)
                session = self._live.get(sid)
                if session is not None:
                    self._live.move_to_end(sid)
                    self.hits += 1
                    return session
                ck = self._checkpoints.get(sid)
                if ck is None:
                    raise KeyError(f"unknown session {sid!r}")
            t0 = time.perf_counter()
            with perf.stage("restore"):
                session = ck.restore()
            self.restores += 1
            self.restore_s += time.perf_counter() - t0
            perf.add("serve_restores")
            flight.note("pool.restore", session=sid, n_toas=ck.n_toas,
                        restore_ms=round(
                            (time.perf_counter() - t0) * 1e3, 3))
            log.info(f"restored session {sid!r} from checkpoint "
                     f"({ck.n_toas} TOAs)")
            self.put(sid, session)
            return session

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "live": len(self._live),
            "checkpointed": len(self._checkpoints),
            "hits": self.hits,
            "evictions": self.evictions,
            "restores": self.restores,
            "restore_s": round(self.restore_s, 4),
        }
