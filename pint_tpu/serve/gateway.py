"""Async HTTP front-end over the serving engine, and the fleet fan-out.

Two layers, same stdlib discipline as the metrics endpoint
(obs/metrics.py — ``ThreadingHTTPServer`` on 127.0.0.1, daemon threads,
``port=0`` binds ephemeral):

- :class:`Gateway` — one replica's wire surface over
  ``ServingEngine.submit`` / :class:`~pint_tpu.serve.engine.ServeTicket`.
  The handler scopes are ASYNC BY CONSTRUCTION: they admit (``submit``),
  poll tickets and read telemetry — never a synchronous fit/append/
  drain — and the ``blocking-in-gateway`` lint rule
  (pint_tpu/analysis/lint.py) fails the build if a blocking engine call
  ever creeps into one. The trace id minted at submit rides back as the
  ``X-Pint-Trace`` response header; admission sheds map to HTTP 429
  (rate/queue refusals) and 503 (draining / quarantined / refused under
  ``PINT_TPU_DEGRADED=error``), queued-past-deadline to 504.
- :class:`FleetGateway` — the front door of a replicated fleet: routes
  each session to its replica by rendezvous hashing
  (serve/route.py; adding a replica moves ~1/R of the sessions),
  honours live-migration pins, aggregates every replica's ``/metrics``
  into one OpenMetrics page (counters summed, latency summaries merged
  LOSSLESSLY via ``QuantileSketch.from_dict`` from each replica's
  ``/v1/sketches``), and drives live migration / kill-absorb through
  the replicas' ``/v1/migrate/*`` control surface (serve/migrate.py).

Wire format: JSON bodies; append rows use the journal's row encoding
(serve/journal.py ``encode_rows``/``decode_rows``), so a gateway client,
a journal record and a replayed request are the same bytes.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from collections import OrderedDict

from pint_tpu.obs import metrics as obs_metrics
from pint_tpu.ops import degrade, perf
from pint_tpu.serve import route
from pint_tpu.serve.journal import JournalError, decode_rows
from pint_tpu.serve.scheduler import (DeadlineError, QuarantinedError,
                                      ShedError)
from pint_tpu.testing import faults
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["Gateway", "FleetGateway", "http_json"]

#: exception -> (HTTP status, stable error kind). Sheds are EXPLICIT
#: refusals the client can act on: 429 = back off and retry (admission
#: rate/queue), 503 = this replica cannot serve you right now
#: (draining, quarantined, refused under PINT_TPU_DEGRADED=error),
#: 504 = the queued request outlived its deadline.
_STATUS = (
    (ShedError, 429, "shed"),
    (DeadlineError, 504, "deadline"),
    (TimeoutError, 504, "timeout"),
    (QuarantinedError, 503, "quarantined"),
    (JournalError, 503, "journal"),
    (degrade.DegradedError, 503, "degraded"),
    (KeyError, 404, "unknown"),
    (ValueError, 400, "bad_request"),
)


def _status_of(exc: BaseException) -> tuple[int, str]:
    from pint_tpu.serve.migrate import MigrateError

    if isinstance(exc, MigrateError):
        return 409, "migrate"
    for cls, code, kind in _STATUS:
        if isinstance(exc, cls):
            return code, kind
    return 500, "internal"


def http_json(url: str, body: dict | None = None, *,
              timeout: float = 30.0) -> tuple[int, dict, dict]:
    """One JSON-over-HTTP exchange (GET when ``body`` is None, POST
    otherwise) against a localhost gateway. Returns ``(status, payload,
    headers)``; non-2xx statuses return their JSON error payload instead
    of raising, so callers branch on status like any HTTP client."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={} if data is None else {"Content-Type":
                                         "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return (resp.status, json.loads(resp.read() or b"{}"),
                    dict(resp.headers))
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            payload = json.loads(raw) if raw else {}
        except ValueError:
            payload = {"error": "internal", "detail": raw.decode(
                "utf-8", "replace")}
        return e.code, payload, dict(e.headers or {})


def _result_block(ticket) -> dict:
    sr = ticket.result
    out = {
        "done": True,
        "idem": ticket.idem,
        "session": ticket.session,
        "kind": ticket.kind,
        "trace": ticket.trace_id,
        "latency_ms": ticket.latency_ms,
        "queue_ms": ticket.queue_ms,
    }
    if sr is not None:
        out.update(path=sr.path, k=sr.k, solve_latency_ms=sr.latency_ms,
                   reason=sr.reason)
    return out


class _HttpServerMixin:
    """Shared stdlib-server plumbing (the obs/metrics.py discipline):
    127.0.0.1 only, ephemeral port on 0, daemon serve thread."""

    _name = "pint-tpu-gateway"

    def _serve(self, handler_cls, port: int) -> int:
        from http.server import ThreadingHTTPServer

        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          handler_cls)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=self._name, daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if getattr(self, "_httpd", None) is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if getattr(self, "_thread", None) is not None:
            self._thread.join(5.0)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


class Gateway(_HttpServerMixin):
    """One serving replica's HTTP surface (see module docstring).

    Endpoints::

        POST /v1/submit       admit a request; ?wait=1 (default) blocks
                              for the result, wait=0 answers 202 + the
                              ticket id for later /v1/tickets polls
        GET  /v1/tickets/<id> poll an async ticket by idempotency key
        GET  /v1/sessions     session ids this replica owns
        GET  /v1/params?session=<sid>   fitted parameters (parity checks)
        GET  /v1/stats        engine.stats() snapshot
        GET  /v1/sketches     latency QuantileSketches, marshalled for
                              lossless cross-process merging
        GET  /v1/degraded     this process's degradation-ledger block
        GET  /metrics         OpenMetrics (process registry)
        GET  /healthz         engine readiness (200/503 + JSON detail)
        POST /v1/checkpoint   durably checkpoint the fleet + compact WAL
        POST /v1/migrate/out  export a session into a handoff dir
        POST /v1/migrate/in   import a handed-off session
        POST /v1/fault        arm a fault spec in THIS process (drills)
        POST /v1/knob         set a registered PINT_TPU_* knob
        POST /v1/stop         stop serving (?drain=1 flushes + closes)
    """

    def __init__(self, engine, port: int | None = None):
        self.engine = engine
        self.port = (int(knobs.get("PINT_TPU_GATEWAY_PORT"))
                     if port is None else int(port))
        self._httpd = None
        self._thread = None
        self.stopped = threading.Event()
        # bounded async-ticket registry: idem -> ServeTicket, oldest
        # dropped (a client that never polls must not leak tickets)
        self._tickets: OrderedDict[str, object] = OrderedDict()
        self._tlock = threading.Lock()

    # -- request plumbing (called from handler scopes) ---------------------------

    def _remember(self, ticket) -> None:
        with self._tlock:
            self._tickets[ticket.idem] = ticket
            while len(self._tickets) > 1024:
                self._tickets.popitem(last=False)

    def _ticket(self, idem: str):
        with self._tlock:
            return self._tickets.get(idem)

    def _submit(self, body: dict, wait: bool, timeout: float) -> tuple:
        """Admit one wire request; returns (status, payload, trace_id).
        The ONLY engine calls here are ``submit`` and a ticket wait —
        the blocking-in-gateway lint contract."""
        kind = body.get("kind", "append")
        kw = {}
        if kind == "append":
            kw = decode_rows(body["rows"])
        ticket = self.engine.submit(
            session=body["session"], kind=kind,
            tenant=body.get("tenant", "default"),
            deadline_s=body.get("deadline_s"),
            idem=body.get("idem"), **kw)
        if not wait:
            self._remember(ticket)
            return 202, {"done": False, "idem": ticket.idem,
                         "session": ticket.session,
                         "trace": ticket.trace_id}, ticket.trace_id
        ticket.wait(timeout)
        return 200, _result_block(ticket), ticket.trace_id

    def _control(self, path: str, body: dict) -> tuple[int, dict]:
        """POST control surface (checkpoint / migrate / fault / knob /
        stop) — small, explicit, localhost-only."""
        import os

        from pint_tpu.serve import migrate as migrate_mod

        if path == "/v1/checkpoint":
            return 200, {"checkpointed": self.engine.checkpoint()}
        if path == "/v1/migrate/out":
            return 200, migrate_mod.export_session(
                self.engine, body["sid"], body["handoff_dir"])
        if path == "/v1/migrate/in":
            return 200, migrate_mod.import_session(
                self.engine, body["handoff_dir"], sid=body.get("sid"))
        if path == "/v1/fault":
            return 200, {"armed": faults.arm_spec(body["spec"])}
        if path == "/v1/knob":
            name = body["name"]
            if name not in knobs.KNOBS:
                raise KeyError(f"{name} is not a registered knob")
            # the remote-control twin of a shell `export`: bench legs
            # flip e.g. PINT_TPU_DEGRADED inside a running replica
            os.environ[name] = str(body["value"])  # jaxlint: disable=env-read — registered-knob write via the control endpoint
            return 200, {"set": name, "value": str(body["value"])}
        if path == "/v1/stop":
            drain = bool(body.get("drain", True))
            threading.Thread(target=self._late_stop, args=(drain,),
                             daemon=True).start()
            return 200, {"stopping": True, "drain": drain}
        raise KeyError(f"unknown control path {path}")

    def _late_stop(self, drain: bool) -> None:
        self.engine.stop(drain=drain)
        self.stopped.set()
        self.stop()

    def _read(self, path: str, query: dict) -> tuple[int, dict]:
        """GET surface: tickets, sessions, params, stats, sketches."""
        if path.startswith("/v1/tickets/"):
            t = self._ticket(path.rsplit("/", 1)[-1])
            if t is None:
                raise KeyError("unknown ticket")
            if not t.done():
                return 202, {"done": False, "idem": t.idem}
            if t.error is not None:
                code, kind = _status_of(t.error)
                return code, {"done": True, "error": kind,
                              "detail": str(t.error)}
            return 200, _result_block(t)
        if path == "/v1/sessions":
            return 200, {"sessions": self.engine.pool.sids()}
        if path == "/v1/params":
            from pint_tpu.fitting.state import snapshot

            sid = query["session"]
            ses = self.engine.pool.get(sid)
            st = snapshot(ses.fitter)
            return 200, {"session": sid, "n_toas": len(ses.toas),
                         "params": {n: [hi, lo] for n, (hi, lo)
                                    in st.params.items()},
                         "chi2": st.chi2}
        if path == "/v1/stats":
            return 200, self.engine.stats()
        if path == "/v1/sketches":
            return 200, {
                "latency_ms": self.engine.latency.to_dict(),
                "refit_latency_ms": self.engine.refit_latency.to_dict(),
                "queue_wait_ms": self.engine.queue_wait.to_dict(),
                "submit_us": self.engine.submit_lat.to_dict(),
            }
        if path == "/v1/degraded":
            return 200, degrade.degradation_block()
        raise KeyError(f"unknown path {path}")

    def start(self) -> int:
        gw = self

        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 — silence stdlib access logs
                pass

            def _reply(self, code: int, payload: dict,
                       trace_id: str = "") -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if trace_id:
                    self.send_header("X-Pint-Trace", trace_id)
                self.end_headers()
                self.wfile.write(body)

            def _query(self) -> dict:
                from urllib.parse import parse_qsl, urlsplit

                return dict(parse_qsl(urlsplit(self.path).query))

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):  # noqa: N802 — stdlib handler API
                path = self.path.split("?")[0]
                if path == "/metrics":
                    body = obs_metrics.registry().render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/openmetrics-text; "
                                     "version=1.0.0; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/healthz":
                    ok, detail = gw.engine.health()
                    self._reply(200 if ok else 503,
                                dict(detail, ok=bool(ok)))
                    return
                try:
                    code, payload = gw._read(path, self._query())
                except Exception as e:  # noqa: BLE001 — mapped to a wire status, never a stack dump on the socket  # jaxlint: disable=silent-except
                    code, kind = _status_of(e)
                    payload = {"error": kind, "detail": str(e)}
                self._reply(code, payload)

            def do_POST(self):  # noqa: N802 — stdlib handler API
                path = self.path.split("?")[0]
                try:
                    body = self._body()
                    if path == "/v1/submit":
                        q = self._query()
                        wait = q.get("wait", "1") != "0"
                        timeout = float(q.get("timeout_s", "60"))
                        code, payload, tid = gw._submit(
                            body, wait, timeout)
                        self._reply(code, payload, tid)
                        return
                    code, payload = gw._control(path, body)
                except Exception as e:  # noqa: BLE001 — mapped to a wire status, never a stack dump on the socket  # jaxlint: disable=silent-except
                    code, kind = _status_of(e)
                    payload = {"error": kind, "detail": str(e)}
                self._reply(code, payload)

        port = self._serve(Handler, self.port)
        log.info(f"serving gateway on 127.0.0.1:{port} "
                 f"(engine: {len(self.engine.pool.sids())} session(s))")
        return port


class FleetGateway(_HttpServerMixin):
    """The fleet's front door (see module docstring): consistent
    session->replica routing with live-migration pins, proxied submits,
    merged fleet-wide telemetry, and the absorb path that moves a dead
    replica's sessions onto the survivors with zero lost requests."""

    _name = "pint-tpu-fleet-gateway"

    def __init__(self, port: int = 0, handoff_root=None):
        from pathlib import Path

        self.port = int(port)
        self._httpd = None
        self._thread = None
        #: replica name -> {"url": base url, "dir": durable dir}
        self.replicas: dict[str, dict] = {}
        #: session -> replica name (rendezvous placement + migration pins)
        self.sessions: dict[str, str] = {}
        self._lock = threading.RLock()
        self.handoff_root = (None if handoff_root is None
                             else Path(handoff_root))
        # materialize the export registry NOW: Registry.feed drops
        # perf-counter bumps until the singleton exists, and the
        # gateway's serve_gateway_* counters must count from the first
        # proxied request, not from the first /metrics scrape
        obs_metrics.registry()

    # -- membership / routing ----------------------------------------------------

    def add_replica(self, name: str, base_url: str,
                    durable_dir=None) -> list[str]:
        """Register a live replica and adopt the sessions it reports.
        Returns the adopted session ids."""
        code, payload, _ = http_json(base_url + "/v1/sessions")
        owned = payload.get("sessions", []) if code == 200 else []
        with self._lock:
            self.replicas[name] = {"url": base_url,
                                   "dir": (None if durable_dir is None
                                           else str(durable_dir))}
            for sid in owned:
                self.sessions[sid] = name
        return owned

    def replica_for(self, sid: str) -> str:
        """The replica owning ``sid``: its recorded placement (set at
        adoption or by a migration pin), else rendezvous routing over
        the current membership."""
        with self._lock:
            name = self.sessions.get(sid)
            if name is not None and name in self.replicas:
                return name
            name = route.owner(sid, self.replicas)
            self.sessions[sid] = name
            return name

    def _url(self, name: str) -> str:
        with self._lock:
            return self.replicas[name]["url"]

    # -- data path ---------------------------------------------------------------

    def proxy_submit(self, body: dict, wait: bool = True,
                     timeout: float = 60.0) -> tuple[int, dict, dict]:
        """Route one submit to its session's replica. The
        ``serve.migrate:force`` drill hook lives here: tripped, the
        session is live-migrated to another replica FIRST and the
        request then lands on the new owner — proving a migration is
        invisible to the client that triggered it."""
        sid = body["session"]
        name = self.replica_for(sid)
        if (faults.trip("serve.migrate", f"session:{sid}") == "force"
                and len(self.replicas) > 1):
            ranked = route.rank(sid, self.replicas)
            target = next(r for r in ranked if r != name)
            self.migrate(sid, target)
            name = target
        perf.add("serve_gateway_requests")
        code, payload, headers = http_json(
            self._url(name) + f"/v1/submit?wait={'1' if wait else '0'}"
            f"&timeout_s={timeout}", body, timeout=timeout + 10.0)
        if code in (429, 503):
            perf.add("serve_gateway_shed")
        return code, payload, headers

    # -- control path ------------------------------------------------------------

    def migrate(self, sid: str, target: str) -> dict:
        """Live-migrate ``sid`` onto replica ``target`` (checkpoint +
        journal-suffix handoff, serve/migrate.py) and pin it there.
        Bounded by ``PINT_TPU_MIGRATE_TIMEOUT_S``; a failed export
        leaves the session on the source."""
        from pint_tpu.serve.migrate import MigrateError

        budget = float(knobs.get("PINT_TPU_MIGRATE_TIMEOUT_S"))
        source = self.replica_for(sid)
        if source == target:
            return {"sid": sid, "noop": True}
        if self.handoff_root is None:
            raise MigrateError("FleetGateway needs a handoff_root to "
                               "migrate sessions")
        handoff = self.handoff_root / f"handoff-{sid}"
        code, out, _ = http_json(
            self._url(source) + "/v1/migrate/out",
            {"sid": sid, "handoff_dir": str(handoff)}, timeout=budget)
        if code != 200:
            raise MigrateError(
                f"export of {sid!r} from {source} failed: {out}")
        code, inp, _ = http_json(
            self._url(target) + "/v1/migrate/in",
            {"sid": sid, "handoff_dir": str(handoff)}, timeout=budget)
        if code != 200:
            raise MigrateError(
                f"import of {sid!r} into {target} failed: {out}")
        with self._lock:
            self.sessions[sid] = target
        log.info(f"migrated session {sid!r}: {source} -> {target}")
        return dict(out, **inp, source=source, target=target)

    def absorb(self, victim: str) -> dict:
        """A replica died: drop it from membership and import every
        session it owned onto the survivors — straight from the victim's
        durable store (same layout as a migration handoff: checkpoints +
        journal), so the absorb replays the victim's un-checkpointed
        tail with idempotency dedup and loses nothing. Rendezvous
        routing picks each session's new home without a handoff table."""
        with self._lock:
            dead = self.replicas.pop(victim)
            orphans = sorted(s for s, n in self.sessions.items()
                             if n == victim)
            survivors = dict(self.replicas)
        if not survivors:
            raise RuntimeError("no surviving replicas to absorb into")
        degrade.record(
            "serve.replica_lost", f"replica:{victim}",
            f"serving replica {victim!r} was lost; {len(orphans)} "
            "session(s) reassigned to the survivors from its durable "
            "checkpoints + journal suffix",
            bound_us=0.0,          # accuracy preserved; a failover pause
            fix="restart the replica and re-add it; rendezvous routing "
                "will move ~1/R of the sessions back")
        perf.add("serve_replicas_lost")
        report = {"victim": victim, "sessions": orphans, "replayed": 0,
                  "deduped": 0, "requests_lost": 0}
        for sid in orphans:
            name = route.owner(sid, survivors)
            code, out, _ = http_json(
                self._url(name) + "/v1/migrate/in",
                {"sid": sid, "handoff_dir": dead["dir"]},
                timeout=float(knobs.get("PINT_TPU_MIGRATE_TIMEOUT_S")))
            if code != 200:
                raise RuntimeError(
                    f"absorb of {sid!r} into {name} failed: {out}")
            with self._lock:
                self.sessions[sid] = name
            for k in ("replayed", "deduped", "requests_lost"):
                report[k] += out.get(k, 0)
        log.info(f"absorbed replica {victim!r}: {len(orphans)} "
                 f"session(s) onto {sorted(survivors)} "
                 f"({report['replayed']} replayed, "
                 f"{report['requests_lost']} lost)")
        return report

    # -- merged telemetry --------------------------------------------------------

    def merged_sketches(self) -> dict:
        """Fleet-wide latency sketches: every replica's marshalled
        QuantileSketches folded grid-exactly (perf.QuantileSketch
        merge) — fleet p50/p99 with zero information loss."""
        merged: dict[str, perf.QuantileSketch] = {}
        with self._lock:
            urls = [r["url"] for r in self.replicas.values()]
        for u in urls:
            code, payload, _ = http_json(u + "/v1/sketches")
            if code != 200:
                continue
            for name, d in payload.items():
                sk = perf.QuantileSketch.from_dict(d)
                if name in merged:
                    merged[name].merge(sk)
                else:
                    merged[name] = sk
        return merged

    def render_metrics(self) -> str:
        """One OpenMetrics page for the whole fleet: replica counters
        and gauges summed sample-by-sample, summary quantiles replaced
        by the LOSSLESSLY merged fleet sketches, this process's own
        gateway counters included."""
        totals: dict[str, float] = {}
        texts = [obs_metrics.registry().render()]
        with self._lock:
            urls = [r["url"] for r in self.replicas.values()]
        for u in urls:
            try:
                with urllib.request.urlopen(u + "/metrics",
                                            timeout=10.0) as resp:
                    texts.append(resp.read().decode())
            except (OSError, urllib.error.URLError):
                continue           # a dead replica scrapes as absent
        for t in texts:
            samples, _ = obs_metrics.parse_openmetrics(t)
            for k, v in samples.items():
                if 'quantile="' in k:
                    continue       # per-replica quantiles do not sum
                totals[k] = totals.get(k, 0.0) + v
        lines = [f"{k} {v:g}" for k, v in sorted(totals.items())]
        for name, sk in sorted(self.merged_sketches().items()):
            full = obs_metrics.PREFIX + "fleet_" + name
            for q in (0.5, 0.9, 0.99):
                v = sk.quantile(q)
                if v is not None:
                    lines.append(f'{full}{{quantile="{q:g}"}} {v:g}')
        lines.append("# EOF")
        return "\n".join(lines)

    def health(self) -> tuple[bool, dict]:
        with self._lock:
            members = dict(self.replicas)
        detail = {"replicas": {}, "sessions": len(self.sessions)}
        ok = bool(members)
        for name, r in members.items():
            code, payload, _ = http_json(r["url"] + "/healthz",
                                         timeout=10.0)
            detail["replicas"][name] = {"ok": code == 200,
                                        "queued": payload.get("queued")}
            ok = ok and code == 200
        return ok, detail

    # -- the HTTP front door -----------------------------------------------------

    def start(self) -> int:
        fg = self

        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 — silence stdlib access logs
                pass

            def _reply(self, code: int, body: bytes, ctype: str,
                       headers: dict | None = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None) -> None:
                self._reply(code, json.dumps(payload).encode(),
                            "application/json", headers)

            def do_GET(self):  # noqa: N802 — stdlib handler API
                path = self.path.split("?")[0]
                if path == "/metrics":
                    self._reply(200, fg.render_metrics().encode(),
                                "application/openmetrics-text; "
                                "version=1.0.0; charset=utf-8")
                    return
                if path == "/healthz":
                    ok, detail = fg.health()
                    self._json(200 if ok else 503,
                               dict(detail, ok=bool(ok)))
                    return
                if path == "/v1/sketches":
                    self._json(200, {n: sk.to_dict() for n, sk in
                                     fg.merged_sketches().items()})
                    return
                if path == "/v1/sessions":
                    self._json(200, {"sessions": dict(fg.sessions)})
                    return
                self._json(404, {"error": "unknown",
                                 "detail": path})

            def do_POST(self):  # noqa: N802 — stdlib handler API
                from urllib.parse import parse_qsl, urlsplit

                path = self.path.split("?")[0]
                q = dict(parse_qsl(urlsplit(self.path).query))
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if path == "/v1/submit":
                        code, payload, hdrs = fg.proxy_submit(
                            body, wait=q.get("wait", "1") != "0",
                            timeout=float(q.get("timeout_s", "60")))
                        tid = hdrs.get("X-Pint-Trace", "")
                        self._json(code, payload,
                                   {"X-Pint-Trace": tid} if tid else None)
                        return
                    if path == "/v1/migrate":
                        self._json(200, fg.migrate(body["sid"],
                                                   body["target"]))
                        return
                    if path == "/v1/absorb":
                        self._json(200, fg.absorb(body["victim"]))
                        return
                    self._json(404, {"error": "unknown", "detail": path})
                except Exception as e:  # noqa: BLE001 — mapped to a wire status, never a stack dump on the socket  # jaxlint: disable=silent-except
                    code, kind = _status_of(e)
                    self._json(code, {"error": kind, "detail": str(e)})

        port = self._serve(Handler, self.port)
        log.info(f"fleet gateway on 127.0.0.1:{port} "
                 f"({len(self.replicas)} replica(s))")
        return port
