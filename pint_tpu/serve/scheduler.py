"""Admission control + continuous batching for the serving engine.

A resident session answers one append in ~tens of ms; "heavy traffic
from millions of users" is not one append — it is an unbounded stream of
them, bursty per pulsar and uneven per tenant. This module holds the two
decisions an always-on server makes BEFORE any device work runs:

- **Admission** (:class:`AdmissionController`): is there room for this
  request at all? A bounded queue (``PINT_TPU_SERVE_QUEUE_DEPTH``) and
  per-tenant token buckets (``PINT_TPU_SERVE_TENANT_RPS``) turn overload
  into an *explicit, ledger-visible shed* (``serve.shed``,
  ops/degrade.py) instead of a collapsing p99: the shed policy
  (``PINT_TPU_SERVE_SHED_POLICY``) either refuses the new request
  (``reject``) or drops the oldest queued one (``drop_oldest``), and
  under ``PINT_TPU_DEGRADED=error`` the ledger write itself raises — the
  production refusal.
- **Batching** (:class:`ContinuousBatchScheduler`): admitted requests
  wait in *lanes* — one per (session) for appends, one per (fit-kind,
  row-bucket) skeleton class for cross-session refits — and a lane
  dispatches the moment it FILLS (enough rows to pack the fixed-shape
  append bucket, enough sessions to fill a fleet bucket) or its oldest
  request hits the deadline (``PINT_TPU_SERVE_MAX_WAIT_MS``). The
  deadline-vs-occupancy tradeoff is driven by live telemetry: the
  padding-waste fraction of recent dispatches (the same
  ``padding_waste_frac`` the fleet engine reports) feeds an EWMA that
  STRETCHES the effective wait when buckets go out underfilled, and
  queue pressure (depth approaching capacity) SHRINKS it — padding waste
  becomes a load-balancing signal instead of a post-hoc metric.

Everything here is host bookkeeping with an injectable clock: tests
drive deadlines and token buckets deterministically, no sleeps.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from pint_tpu.ops import degrade, perf
from pint_tpu.testing import faults
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["AdmissionController", "ContinuousBatchScheduler",
           "DeadlineError", "Lane", "QuarantinedError", "ShedError",
           "TokenBucket"]


class ShedError(RuntimeError):
    """The request was refused or dropped by serving admission control.

    Raised to the SUBMITTER (for ``reject``) or delivered through the
    dropped request's ticket (for ``drop_oldest``) — in both cases after
    the ``serve.shed`` degradation event is on the ledger, so the shed
    is observable even when the client swallows the error."""


class DeadlineError(RuntimeError):
    """The request's deadline expired while it was still queued; it was
    shed instead of occupying a dispatch slot (``serve.deadline`` on the
    degradation ledger, delivered through the ticket)."""


class QuarantinedError(RuntimeError):
    """The target session is quarantined (a hung or crash-looping lane,
    ``serve.quarantine`` on the degradation ledger); requests for it are
    refused while the rest of the fleet keeps serving."""


class TokenBucket:
    """Per-tenant request-rate limiter: ``rate`` tokens/s refill up to
    ``burst``; a request takes one token or is shed. ``rate <= 0``
    disables the bucket (always admits)."""

    def __init__(self, rate: float, burst: float | None = None,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(
            self.rate, 1.0)
        self._tokens = self.burst
        self._clock = clock
        self._t_last = clock()

    def try_take(self) -> bool:
        if self.rate <= 0:
            return True
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t_last) * self.rate)
        self._t_last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class AdmissionController:
    """Bounded-queue + per-tenant-rate admission with an explicit
    overload policy. One instance guards one serving engine's queue.

    :meth:`admit` returns ``"admit"`` (room available) or
    ``"drop_oldest"`` (the caller must shed its oldest queued request to
    make room — only under that policy), and raises :class:`ShedError`
    (or :class:`~pint_tpu.ops.degrade.DegradedError` under
    ``PINT_TPU_DEGRADED=error``) when the request itself is shed. Every
    shed records ``serve.shed`` on the degradation ledger and bumps the
    ``serve_shed`` telemetry counter BEFORE any raise."""

    def __init__(self, max_depth: int | None = None,
                 tenant_rps: float | None = None,
                 policy: str | None = None, clock=time.monotonic):
        self.max_depth = int(knobs.get("PINT_TPU_SERVE_QUEUE_DEPTH")) \
            if max_depth is None else int(max_depth)
        self.tenant_rps = float(knobs.get("PINT_TPU_SERVE_TENANT_RPS")) \
            if tenant_rps is None else float(tenant_rps)
        policy = (knobs.get("PINT_TPU_SERVE_SHED_POLICY")
                  if policy is None else policy) or "reject"
        if policy not in ("reject", "drop_oldest"):
            raise ValueError(
                f"unknown shed policy {policy!r} "
                "(PINT_TPU_SERVE_SHED_POLICY: reject | drop_oldest)")
        self.policy = policy
        self._clock = clock
        self._buckets: dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        #: total requests shed (refused or dropped) by this controller
        self.shed_count = 0

    def _shed(self, tenant: str, why: str, detail: str) -> None:
        with self._lock:
            self.shed_count += 1
        perf.add("serve_shed")
        # the ledger write happens FIRST: under PINT_TPU_DEGRADED=error
        # it raises DegradedError (the production refusal) with the shed
        # already on the record; otherwise the caller gets ShedError
        degrade.record(
            "serve.shed", f"serve:{why}",
            detail,
            bound_us=0.0,  # accuracy untouched; availability degraded
            fix="raise PINT_TPU_SERVE_QUEUE_DEPTH / "
                "PINT_TPU_SERVE_TENANT_RPS, add capacity, or shed by "
                "design (PINT_TPU_SERVE_SHED_POLICY)")
        raise ShedError(detail)

    def admit(self, tenant: str, depth: int) -> str:
        """Admit one request from ``tenant`` given the current queue
        ``depth``; see the class docstring for outcomes."""
        if faults.trip("serve.admit", f"tenant:{tenant}") is not None:
            self._shed(tenant, "fault",
                       f"fault-injected shed for tenant {tenant!r} "
                       "(PINT_TPU_FAULTS=serve.admit:shed)")
        if self.tenant_rps > 0:
            with self._lock:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = TokenBucket(
                        self.tenant_rps, clock=self._clock)
            if not bucket.try_take():
                self._shed(tenant, "rate",
                           f"tenant {tenant!r} exceeded "
                           f"{self.tenant_rps:g} requests/s "
                           "(PINT_TPU_SERVE_TENANT_RPS)")
        if depth >= self.max_depth:
            if self.policy == "drop_oldest":
                return "drop_oldest"
            self._shed(tenant, "depth",
                       f"queue depth {depth} at capacity "
                       f"{self.max_depth} (PINT_TPU_SERVE_QUEUE_DEPTH); "
                       f"request from tenant {tenant!r} refused")
        return "admit"

    def refuse(self, tenant: str, why: str, detail: str) -> None:
        """Shed one request for a reason OUTSIDE the depth/rate checks
        (e.g. the engine refusing new work while draining): same ledger
        write, same counters, same :class:`ShedError` (or
        ``DegradedError``) as any other shed."""
        self._shed(tenant, why, detail)

    def record_drop(self, tenant: str, detail: str) -> None:
        """Ledger + counters for a ``drop_oldest`` shed (the DROPPED
        request's side — :meth:`admit` already told the caller to make
        room). Never raises ShedError at the submit site; under
        ``PINT_TPU_DEGRADED=error`` the ledger write still refuses."""
        with self._lock:
            self.shed_count += 1
        perf.add("serve_shed")
        degrade.record(
            "serve.shed", "serve:drop_oldest", detail, bound_us=0.0,
            fix="raise PINT_TPU_SERVE_QUEUE_DEPTH or add capacity")


@dataclass
class Lane:
    """One dispatch queue: same-session appends, or one refit skeleton
    class. ``rows`` counts payload rows (appends) or member sessions
    (refits) toward the fill target."""

    key: tuple
    kind: str                      # "append" | "refit"
    sid: str | None = None         # append lanes: the session
    tickets: list = field(default_factory=list)
    rows: int = 0
    t_oldest: float = 0.0

    def age_s(self, now: float) -> float:
        return (now - self.t_oldest) if self.tickets else 0.0


class ContinuousBatchScheduler:
    """Lane bookkeeping for continuous batching (see module docstring).

    The engine offers admitted tickets into lanes and calls :meth:`due`
    every loop turn; lanes come back the moment they fill or their
    oldest ticket ages past the *effective* wait — the base deadline
    scaled by the padding-waste EWMA (underfilled dispatches → stretch,
    up to 4x) and by queue pressure (depth ≥ half capacity → shrink to a
    quarter). Appends dispatch at most ``coalesce_rows`` rows per batch:
    that keeps every coalesced append inside the same fixed-shape
    device bucket the session pre-warmed, so continuous batching never
    costs a retrace."""

    def __init__(self, max_wait_ms: float | None = None,
                 coalesce_rows: int = 16, refit_batch: int = 4,
                 waste_alpha: float = 0.3, clock=time.monotonic):
        self.base_wait_s = (float(knobs.get("PINT_TPU_SERVE_MAX_WAIT_MS"))
                            if max_wait_ms is None
                            else float(max_wait_ms)) * 1e-3
        self.coalesce_rows = int(coalesce_rows)
        self.refit_batch = int(refit_batch)
        self._clock = clock
        self._lanes: dict[tuple, Lane] = {}
        self._depth = 0
        self._waste_ewma = 0.0
        self._waste_alpha = float(waste_alpha)
        self._lock = threading.Lock()

    # -- state ---------------------------------------------------------------------

    def depth(self) -> int:
        """Tickets currently queued across all lanes."""
        with self._lock:
            return self._depth

    @property
    def waste_ewma(self) -> float:
        return self._waste_ewma

    def observe_waste(self, frac: float | None) -> None:
        """Fold one dispatch's padding-waste fraction (fraction of
        padded rows that were padding — the fleet engine's
        ``padding_waste_frac``, or ``1 - k/bucket`` for a rank-k append)
        into the EWMA steering the deadline."""
        if frac is None:
            return
        frac = min(max(float(frac), 0.0), 1.0)
        with self._lock:
            self._waste_ewma += self._waste_alpha * (frac - self._waste_ewma)

    def effective_wait_s(self, capacity: int) -> float:
        """The live deadline: base max-wait stretched by the waste EWMA
        (an underfilled fleet is cheap patience) and collapsed under
        queue pressure (a deep queue needs latency, not occupancy)."""
        with self._lock:
            wait = self.base_wait_s * (1.0 + 3.0 * self._waste_ewma)
            wait = min(wait, 4.0 * self.base_wait_s)
            if capacity > 0 and self._depth >= 0.5 * capacity:
                wait = 0.25 * self.base_wait_s
        perf.put("serve_eff_wait_ms", round(wait * 1e3, 3))
        perf.put("serve_waste_ewma", round(self._waste_ewma, 4))
        return wait

    # -- lane traffic ----------------------------------------------------------------

    def offer(self, ticket, *, rows: int = 1) -> None:
        """Queue one admitted ticket into its lane."""
        now = self._clock()
        with self._lock:
            lane = self._lanes.get(ticket.lane_key)
            if lane is None:
                lane = self._lanes[ticket.lane_key] = Lane(
                    ticket.lane_key, ticket.kind,
                    sid=ticket.session if ticket.kind == "append" else None)
            if not lane.tickets:
                lane.t_oldest = now
            lane.tickets.append(ticket)
            lane.rows += rows
            self._depth += 1

    def drop_oldest(self):
        """Pop the globally oldest queued ticket (the ``drop_oldest``
        shed policy's victim); None when nothing is queued."""
        with self._lock:
            oldest, lane_at = None, None
            for lane in self._lanes.values():
                if lane.tickets and (oldest is None
                                     or lane.t_oldest < oldest):
                    oldest, lane_at = lane.t_oldest, lane
            if lane_at is None:
                return None
            t = lane_at.tickets.pop(0)
            lane_at.rows -= getattr(t, "rows", 1)
            self._depth -= 1
            if lane_at.tickets:
                lane_at.t_oldest = getattr(lane_at.tickets[0], "t_submit",
                                           self._clock())
            return t

    def expire(self, now: float) -> list:
        """Pop every queued ticket whose absolute request deadline has
        passed — expired work is shed (``serve.deadline``, engine-side)
        instead of occupying a dispatch slot. Returns the expired
        tickets, oldest first."""
        out = []
        with self._lock:
            for lane in self._lanes.values():
                if not lane.tickets:
                    continue
                keep = []
                for t in lane.tickets:
                    dl = getattr(t, "deadline", None)
                    if dl is not None and now >= dl:
                        out.append(t)
                        self._depth -= 1
                    else:
                        keep.append(t)
                if len(keep) != len(lane.tickets):
                    lane.tickets = keep
                    lane.rows = sum(getattr(t, "rows", 1) for t in keep)
                    if keep:
                        lane.t_oldest = getattr(keep[0], "t_submit", now)
        return sorted(out, key=lambda t: getattr(t, "t_submit", 0.0))

    def next_deadline(self, capacity: int) -> float | None:
        """Absolute clock time of the earliest lane deadline (None when
        idle) — the worker's bounded wait."""
        wait = self.effective_wait_s(capacity)
        with self._lock:
            ts = [lane.t_oldest + wait
                  for lane in self._lanes.values() if lane.tickets]
        return min(ts) if ts else None

    def due(self, capacity: int, append_cap=None) -> list[Lane]:
        """Pop every lane ready to dispatch NOW: full (appends — enough
        rows to fill the coalesce bucket, capped per session by
        ``append_cap(sid)`` so a dispatch never leaves the incremental
        staleness envelope; refits — ``refit_batch`` members) or past
        the effective deadline. Append lanes with more queued rows than
        one bucket dispatch the HEAD of the lane and keep the rest
        queued — continuous batching, not drain-the-world."""
        now = self._clock()
        wait = self.effective_wait_s(capacity)
        out: list[Lane] = []
        with self._lock:
            for key in list(self._lanes):
                lane = self._lanes[key]
                if not lane.tickets:
                    continue
                cap = self.coalesce_rows
                if lane.kind == "append" and append_cap is not None:
                    cap = max(1, min(cap, int(append_cap(lane.sid))))
                full = (lane.rows >= cap if lane.kind == "append"
                        else len(lane.tickets) >= self.refit_batch)
                if not full and (now - lane.t_oldest) < wait:
                    continue
                if lane.kind == "append":
                    head, rows = [], 0
                    while lane.tickets:
                        t = lane.tickets[0]
                        r = getattr(t, "rows", 1)
                        if head and rows + r > cap:
                            break
                        head.append(lane.tickets.pop(0))
                        rows += r
                    batch = Lane(lane.key, lane.kind, sid=lane.sid,
                                 tickets=head, rows=rows,
                                 t_oldest=lane.t_oldest)
                    lane.rows -= rows
                    if lane.tickets:
                        lane.t_oldest = now
                else:
                    batch = Lane(lane.key, lane.kind, tickets=lane.tickets,
                                 rows=lane.rows, t_oldest=lane.t_oldest)
                    lane.tickets, lane.rows = [], 0
                self._depth -= len(batch.tickets)
                out.append(batch)
        return out
