"""Write-ahead request journal: accepted work survives the process.

The serving engine's contract so far was *availability* — shed under
overload, evict under memory pressure, never collapse. This module adds
*durability*: every admitted request is appended to an on-disk journal
BEFORE its ticket acks admission, so a crash between admission and
finalize loses nothing — a fresh process replays the suffix
(serve/recover.py) and answers exactly what the dead one would have.

Layout (one directory per engine)::

    <dir>/journal-000001.wal      closed segments (older first)
    <dir>/journal-000007.wal      the active segment (appended live)
    <dir>/quarantine/…            checksum-corrupt segments, preserved

Each segment is a stream of framed records::

    <u32 payload length> <u32 crc32(payload)> <payload: one JSON object>

JSON keeps records inspectable with nothing but ``python -m json.tool``
(the payload floats round-trip exactly — Python emits shortest-repr
doubles); the frame makes torn writes and bit rot detectable per record.
Record kinds (the ``op`` field):

- ``request`` — one admitted request: session, kind (append/refit),
  tenant, idempotency key, absolute deadline, and the raw TOA rows for
  appends. Appended (and flushed to the OS) before ``submit`` returns.
- ``checkpoint`` — a fleet-checkpoint boundary (serve/recover.py
  ``checkpoint_fleet``): every earlier record is captured by the
  session checkpoints, so :meth:`RequestJournal.mark_checkpoint` rotates
  to a fresh segment and DELETES the superseded ones — the journal never
  grows past one checkpoint interval.
- ``close`` — a clean shutdown (``ServingEngine.stop(drain=True)``):
  the queue was flushed and the fleet checkpointed, so recovery takes
  the fast no-replay path.

Durability knobs: writes always reach the OS (``flush`` per record — a
killed *process* loses nothing, which is what the ``serve.crash`` drill
proves), and ``PINT_TPU_SERVE_JOURNAL_FSYNC`` batches the fsyncs that
survive a killed *machine* (every N records; rotation, checkpoint and
close always fsync).

Failure handling on read (:func:`replay_records`) follows the fetch
quarantine discipline — never silently skip:

- a torn FINAL record (the process died mid-write) is expected crash
  debris: recovery keeps every whole record, records
  ``serve.journal_truncated`` on the degradation ledger, and truncates
  the segment so the journal is whole again;
- a checksum-corrupt record (or a torn record anywhere but the live
  tail) means storage lied: the segment is copied into ``quarantine/``
  beside the journal, ``serve.journal_corrupt`` goes on the ledger
  (refusable under ``PINT_TPU_DEGRADED=error``), and only the records
  before the corruption are served.

The ``serve.journal:torn`` fault site (testing/faults.py) writes a
genuinely torn frame and raises, so the recovery path is drillable
end-to-end without killing anything.
"""

from __future__ import annotations

import errno
import json
import os
import shutil
import struct
import threading
import time
import zlib
from pathlib import Path

import numpy as np

from pint_tpu.obs import flight, metrics as obs_metrics
from pint_tpu.ops import degrade, perf
from pint_tpu.testing import faults
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["JournalError", "RequestJournal", "encode_rows", "decode_rows",
           "replay_records"]

_FRAME = struct.Struct("<II")          # payload length, crc32(payload)
_SEGMENT_GLOB = "journal-*.wal"


class JournalError(OSError):
    """The write-ahead journal could not durably record a request; the
    request was NOT acked (submit re-raises this to the client)."""


def encode_rows(payload: dict) -> dict:
    """JSON-ready form of an append payload (the raw TOA rows a
    :meth:`ServingEngine.submit` call carries): the exact (day, frac_hi,
    frac_lo) epoch triple plus errors/frequencies/observatories/flags.
    Floats survive JSON exactly (shortest-repr round-trip), so a
    replayed request prepares bit-identical rows."""
    ep = payload["utc"]
    return {
        "day": np.asarray(ep.day).astype(int).tolist(),
        "frac_hi": np.asarray(ep.frac_hi).astype(float).tolist(),
        "frac_lo": np.asarray(ep.frac_lo).astype(float).tolist(),
        "error_us": np.asarray(payload["error_us"]).astype(float).tolist(),
        "freq_mhz": np.asarray(payload["freq_mhz"]).astype(float).tolist(),
        "obs": [str(o) for o in np.asarray(payload["obs"])],
        "flags": [dict(f) for f in (payload.get("flags") or
                                    [{} for _ in np.asarray(
                                        payload["error_us"])])],
    }


def decode_rows(rows: dict) -> dict:
    """Inverse of :func:`encode_rows`: the kwargs ``TimingSession.append``
    (and ``ServingEngine.submit``) take."""
    from pint_tpu.astro import time as ptime

    return {
        "utc": ptime.MJDEpoch(np.asarray(rows["day"], dtype=np.int64),
                              np.asarray(rows["frac_hi"], dtype=np.float64),
                              np.asarray(rows["frac_lo"], dtype=np.float64)),
        "error_us": np.asarray(rows["error_us"], dtype=np.float64),
        "freq_mhz": np.asarray(rows["freq_mhz"], dtype=np.float64),
        "obs": np.asarray(rows["obs"]),
        "flags": [dict(f) for f in rows["flags"]],
    }


def _segment_index(path: Path) -> int:
    return int(path.stem.split("-")[-1])


def _segments(dirpath: Path) -> list[Path]:
    return sorted(dirpath.glob(_SEGMENT_GLOB), key=_segment_index)


class RequestJournal:
    """Segmented, checksummed, fsync-batched write-ahead log (see module
    docstring). One instance owns one directory; appends are serialized
    by an internal lock so concurrent client submits interleave whole
    records, never bytes."""

    def __init__(self, dirpath: str | Path, fsync_every: int | None = None):
        self.dir = Path(dirpath)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fsync_every = (int(knobs.get("PINT_TPU_SERVE_JOURNAL_FSYNC"))
                            if fsync_every is None else int(fsync_every))
        self._lock = threading.Lock()
        self._unsynced = 0
        self.seq = 0                       # monotonic record number
        self.appended = 0                  # request records this process
        existing = _segments(self.dir)
        # a reopened journal (recovery) continues in a FRESH segment: the
        # old ones stay replayable until the next checkpoint compacts them
        self._seg_index = (_segment_index(existing[-1]) + 1 if existing
                          else 1)
        self._fh = self._open_segment()

    # -- segment plumbing ------------------------------------------------------------

    def _seg_path(self, index: int) -> Path:
        return self.dir / f"journal-{index:06d}.wal"

    def _open_segment(self):
        return open(self._seg_path(self._seg_index), "ab")

    @property
    def active_segment(self) -> Path:
        return self._seg_path(self._seg_index)

    def segments(self) -> list[Path]:
        """Every live (non-quarantined) segment, oldest first."""
        return _segments(self.dir)

    # -- writes ----------------------------------------------------------------------

    def _write_record(self, rec: dict) -> None:
        self._write_payload(json.dumps(rec, separators=(",", ":")).encode(),
                            rec.get("seq"))

    def _write_payload(self, payload: bytes, seq) -> None:
        frame = _FRAME.pack(len(payload), zlib.crc32(payload))
        mode = faults.trip("serve.journal", f"seq:{seq}")
        if mode == "torn":
            # a genuinely torn frame: the header plus half the payload
            # reach the OS, then the "process dies" (the raise) — the
            # recovery path must stop at the last whole record
            self._fh.write(frame + payload[: max(len(payload) // 2, 1)])
            self._fh.flush()
            os.fsync(self._fh.fileno())
            raise JournalError(
                "injected torn journal write (serve.journal:torn) at "
                f"record seq {seq}")
        if mode == "corrupt":
            # silent bit rot: the frame promises the original crc but
            # the payload lies — only the read path can catch it
            payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
        if mode == "enospc":
            self._shed_full(OSError(errno.ENOSPC,
                                    "injected disk-full on journal append "
                                    "(serve.journal:enospc)"),
                            f"seq:{seq}")
        try:
            self._fh.write(frame + payload)
            # flush every record: the bytes reach the OS before the
            # ticket acks, so a killed process (the serve.crash drill)
            # loses nothing
            self._fh.flush()
        except OSError as e:
            if e.errno == errno.ENOSPC:
                self._shed_full(e, f"seq:{seq}")
            raise
        self._unsynced += 1
        if self.fsync_every > 0 and self._unsynced >= self.fsync_every:
            self._fsync_timed()
            self._unsynced = 0

    def _shed_full(self, e: OSError, context: str) -> None:
        """ENOSPC on an append/fsync: record ``serve.journal_full`` on
        the degradation ledger (refusable under
        ``PINT_TPU_DEGRADED=error`` — the ledger write raises first)
        and shed the write with :class:`JournalError` — the gateway maps
        it to an explicit 503, the request was never acked, and the
        engine keeps serving reads and already-admitted work. Writes
        resume as soon as an append succeeds again; nothing latches."""
        degrade.record(
            "serve.journal_full", self.dir.name,
            f"journal write at {context} hit ENOSPC ({e}); the request "
            "was refused un-acked, reads and admitted work continue",
            fix="free disk space (or compact via checkpoint_fleet) — "
                "writes resume on the next successful append")
        perf.add("serve_journal_full")
        raise JournalError(
            f"write-ahead journal disk full at {context}: the write was "
            "shed (503); reads continue") from e

    def _fsync_timed(self) -> None:
        """fsync with its latency exported: the WAL's durability tax is
        a first-class SLO signal (the serve_journal_fsync_seconds
        summary in the metrics registry)."""
        t0 = time.perf_counter()
        try:
            os.fsync(self._fh.fileno())
        except OSError as e:
            if e.errno == errno.ENOSPC:
                self._shed_full(e, "fsync")
            raise
        obs_metrics.observe("serve_journal_fsync_seconds",
                            time.perf_counter() - t0)

    def append(self, rec: dict) -> int:
        """Durably append one ``request`` record; returns its seq number.
        Called by ``submit`` BEFORE the ticket is queued — a raise here
        means the request was never admitted."""
        # staged as "journal" only: the caller (ServingEngine.submit) is
        # already inside the "serve" root, so the WAL wall lands at
        # serve/journal in the serve_breakdown attribution
        with perf.stage("journal"):
            # two-phase append: the seq reservation is the only thing the
            # JSON encode needs, so the encode — the CPU-bound half of a
            # large-rows append, easily hundreds of µs — runs OUTSIDE the
            # journal lock and concurrent submits serialize only on the
            # actual frame write. Seq order and byte order may differ
            # under contention; replay orders by seq, not byte position.
            with self._lock:
                self.seq += 1
                seq = self.seq
            payload = json.dumps(dict(rec, op="request", seq=seq),
                                 separators=(",", ":")).encode()
            with self._lock:
                self._write_payload(payload, seq)
                self.appended += 1
            perf.add("serve_journal_records")
            return seq

    def mark(self, op: str, **fields) -> int:
        """Durably append (and fsync) a non-request marker record — the
        migration handoff's ``migrate_out``/``migrate_in`` ownership
        markers (serve/migrate.py). Recovery treats the marked session's
        earlier records as moved, not lost."""
        with self._lock:
            self.seq += 1
            self._write_record({"op": op, "seq": self.seq, **fields})
            self._fh.flush()
            self._fsync_timed()
            self._unsynced = 0
            return self.seq

    def fsync(self) -> None:
        """Force the fsync a batched cadence may still owe."""
        with self._lock:
            self._fh.flush()
            self._fsync_timed()
            self._unsynced = 0

    def mark_checkpoint(self, sids: list[str]) -> None:
        """Record a fleet-checkpoint boundary, rotate to a fresh segment
        and DELETE the superseded ones: every record before the marker is
        captured by the session checkpoints (serve/recover.py), so the
        journal's replay suffix — and its disk footprint — restarts at
        zero here."""
        with self._lock:
            self.seq += 1
            self._write_record({"op": "checkpoint", "seq": self.seq,
                                "sids": list(sids)})
            self._fh.flush()
            self._fsync_timed()
            self._unsynced = 0
            self._fh.close()
            old = [p for p in _segments(self.dir)
                   if _segment_index(p) <= self._seg_index]
            self._seg_index += 1
            self._fh = self._open_segment()
            for p in old:
                p.unlink(missing_ok=True)
            perf.add("serve_journal_compactions")
        flight.note("journal.checkpoint", seq=self.seq,
                    compacted=len(old), sids=len(sids))
        log.info(f"journal checkpoint at seq {self.seq}: compacted "
                 f"{len(old)} segment(s), now in "
                 f"{self.active_segment.name}")

    def close(self, clean: bool = True) -> None:
        """Close the journal; ``clean=True`` appends the clean-shutdown
        marker recovery's fast no-replay path keys on (only correct
        after the queue drained AND the fleet checkpointed —
        ``ServingEngine.stop(drain=True)`` is the caller)."""
        with self._lock:
            if self._fh.closed:
                return
            if clean:
                self.seq += 1
                self._write_record({"op": "close", "seq": self.seq})
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()

    def stats(self) -> dict:
        segs = self.segments()
        return {
            "dir": str(self.dir),
            "segments": len(segs),
            "bytes": sum(p.stat().st_size for p in segs),
            "seq": self.seq,
            "appended": self.appended,
            "fsync_every": self.fsync_every,
        }


def _quarantine_segment(path: Path, reason: str) -> None:
    qdir = path.parent / "quarantine"
    qdir.mkdir(parents=True, exist_ok=True)
    shutil.copy2(path, qdir / path.name)
    degrade.record(
        "serve.journal_corrupt", path.name,
        f"journal segment failed validation ({reason}); preserved at "
        f"{qdir / path.name} — records after the corruption point were "
        "NOT replayed",
        fix="inspect the quarantined segment; restore the affected "
            "sessions from their checkpoints and re-submit the lost tail")


def replay_records(dirpath: str | Path) -> tuple[list[dict], dict]:
    """Read every whole record from a journal directory, oldest first.

    Returns ``(records, report)`` where ``report`` carries what the read
    decided: ``clean_close`` (the last record is a ``close`` marker —
    recovery may take the no-replay path), ``truncated_records`` (torn
    final records dropped, with ``serve.journal_truncated`` on the
    ledger), ``corrupt_segments`` (quarantined, ``serve.journal_corrupt``
    on the ledger). Only records after the LAST ``checkpoint`` marker
    are the replay suffix — earlier ones are captured by the session
    checkpoints (and normally already compacted away).
    """
    dirpath = Path(dirpath)
    records: list[dict] = []
    report = {"segments": 0, "clean_close": False,
              "truncated_records": 0, "corrupt_segments": 0}
    segs = _segments(dirpath)
    report["segments"] = len(segs)
    for si, seg in enumerate(segs):
        data = seg.read_bytes()
        off = 0
        is_last_seg = si == len(segs) - 1
        while off < len(data):
            if off + _FRAME.size > len(data):
                break                      # torn frame header
            length, crc = _FRAME.unpack_from(data, off)
            payload = data[off + _FRAME.size: off + _FRAME.size + length]
            if len(payload) < length:
                break                      # torn payload
            if zlib.crc32(payload) != crc:
                _quarantine_segment(
                    seg, f"crc mismatch at offset {off}")
                report["corrupt_segments"] += 1
                off = len(data)            # nothing past the lie is trusted
                break
            try:
                rec = json.loads(payload)
            except ValueError:
                _quarantine_segment(
                    seg, f"undecodable record at offset {off}")
                report["corrupt_segments"] += 1
                off = len(data)
                break
            records.append(rec)
            off += _FRAME.size + length
        if off < len(data):                # a torn (not corrupt) tail
            if is_last_seg:
                # expected crash debris: keep the whole prefix, truncate
                # the segment so the journal is whole again
                with open(seg, "r+b") as fh:
                    fh.truncate(off)
                report["truncated_records"] += 1
                degrade.record(
                    "serve.journal_truncated", seg.name,
                    f"torn final record truncated at byte {off} "
                    f"({len(data) - off} trailing bytes dropped); every "
                    "whole record was recovered",
                    fix="none needed — the torn tail is the crash point; "
                        "the un-acked request was never admitted")
            else:
                # a torn record anywhere else means the storage lied
                _quarantine_segment(
                    seg, f"mid-journal truncation at byte {off}")
                report["corrupt_segments"] += 1
    # canonical order is seq, not byte position: the two-phase append
    # serializes frame writes but not seq reservation, so two contending
    # submits may land on disk swapped — replay must not care
    records.sort(key=lambda r: r.get("seq", 0))
    report["clean_close"] = bool(records) and records[-1]["op"] == "close"
    # the replay suffix: everything after the last checkpoint marker
    last_ck = max((i for i, r in enumerate(records)
                   if r["op"] == "checkpoint"), default=-1)
    if last_ck >= 0:
        records = records[last_ck + 1:]
    return records, report
