"""Resident serving surfaces: warm processes answering timing requests.

The "millions of users" shape (ROADMAP item 3) is not a script — it is a
process that stays up, owns prepared TOAs + a converged fitter + the
incremental-refit state, and answers small appends in milliseconds. This
package holds those surfaces, bottom to top:

- :class:`~pint_tpu.serve.session.TimingSession` /
  :class:`~pint_tpu.serve.session.TimingService` — one resident pulsar /
  a synchronous queue over many (PR 10's engine);
- :class:`~pint_tpu.serve.pool.SessionPool` — the warm LRU pool with
  FitterState checkpoint/restore (zero-trace under
  ``PINT_TPU_EXPECT_WARM=1``);
- :class:`~pint_tpu.serve.engine.ServingEngine` — the always-on
  continuous-batching worker with admission control and load shedding;
- :class:`~pint_tpu.serve.journal.RequestJournal` /
  serve/recover.py — the durability layer: a write-ahead request
  journal ahead of every admission ack, crash-safe cross-process fleet
  recovery (``pint_tpu recover``), deadline/retry/watchdog lifecycle
  hardening;
- :class:`~pint_tpu.serve.gateway.Gateway` /
  :class:`~pint_tpu.serve.gateway.FleetGateway` + serve/fleet.py —
  horizontal scale-out: the async HTTP front-end over the
  ``submit``/ticket surface, R replica worker processes sharing the
  content-addressed warm caches, rendezvous session routing
  (serve/route.py) and live checkpoint-handoff migration
  (serve/migrate.py) with kill-absorb failover.
"""

from pint_tpu.serve.engine import ServeTicket, ServingEngine  # noqa: F401
from pint_tpu.serve.fleet import ReplicaFleet  # noqa: F401
from pint_tpu.serve.gateway import (FleetGateway, Gateway,  # noqa: F401
                                    http_json)
from pint_tpu.serve.journal import (JournalError,  # noqa: F401
                                    RequestJournal, replay_records)
from pint_tpu.serve.migrate import (MigrateError,  # noqa: F401
                                    export_session, import_session,
                                    migrate_session)
from pint_tpu.serve.pool import SessionCheckpoint, SessionPool  # noqa: F401
from pint_tpu.serve.recover import (checkpoint_fleet,  # noqa: F401
                                    recover_fleet)
from pint_tpu.serve.scheduler import (AdmissionController,  # noqa: F401
                                      ContinuousBatchScheduler,
                                      DeadlineError, QuarantinedError,
                                      ShedError, TokenBucket)
from pint_tpu.serve.session import (SessionResult, TimingService,  # noqa: F401
                                    TimingSession, batch_refit,
                                    coalesce_append_payloads)
