"""Resident serving surfaces: warm processes answering timing requests.

The "millions of users" shape (ROADMAP item 4) is not a script — it is a
process that stays up, owns prepared TOAs + a converged fitter + the
incremental-refit state, and answers small appends in milliseconds. This
package holds those surfaces; the future async front-end plugs into
:class:`~pint_tpu.serve.session.TimingSession` /
:class:`~pint_tpu.serve.session.TimingService`.
"""

from pint_tpu.serve.session import SessionResult, TimingService, TimingSession  # noqa: F401
