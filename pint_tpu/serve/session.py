"""Resident timing sessions: the warm process a timing service keeps up.

A :class:`TimingSession` owns one pulsar's prepared TOAs, its converged
downhill fitter, and the incremental-refit engine
(fitting/incremental.py). A k-TOA append is answered by the O(k)
prepared-column append (``TOAs.append``) plus the rank-k
normal-equation update — not a from-scratch prepare + fit — with
per-request latency recorded through ops/perf.py and surfaced as
p50/p99 in :meth:`TimingSession.stats`.

A :class:`TimingService` fronts many sessions: requests queue through
:meth:`~TimingService.submit` and :meth:`~TimingService.drain` answers
them — appends to the same session COALESCE into one rank-k update, and
full-refit requests across sessions batch into the fleet-fit engine's
skeleton buckets (fitting/batch.py ``fit_batch``), so B structurally
identical refits run as one fused device program. Draining is
deterministic: batched ≡ the same requests served one at a time
(locked by tests/test_session.py), because the fleet driver's masked
convergence reproduces every element's solo trajectory.

This is the substrate an async front-end plugs into (ROADMAP item 4):
the request objects are plain dicts, the latency telemetry is already
per-request, and ``PINT_TPU_DEGRADED=error`` turns every silent
corner-cut (including an incremental-refit fallback) into a refusal.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from pint_tpu.ops import perf
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["SessionResult", "TimingSession", "TimingService"]


@dataclass
class SessionResult:
    """One answered request: the fit outcome plus its serving telemetry."""

    result: object                 # FitResult (or None for no-refit appends)
    path: str                      # "incremental" | "full_fallback" | "full" | "append_only"
    k: int                         # rows this request appended
    latency_ms: float
    reason: str | None = None      # fallback reason, when any
    breakdown: dict | None = None  # incremental_breakdown when telemetry on


class TimingSession:
    """One pulsar's resident state: prepared TOAs + converged fitter +
    cached normal-equation blocks, answering appends incrementally.

    Construct with prepared TOAs and a model; :meth:`fit` runs the
    initial full (fused) downhill fit and captures the incremental
    state. Every :meth:`append` then prepares ONLY the new rows, updates
    the cached blocks rank-k, and polishes — falling back to a full warm
    refit (recorded on the degradation ledger) past the staleness
    bounds. The fitter kind follows ``fit_auto`` (WLS / GLS / wideband).
    """

    def __init__(self, toas, model, maxiter: int = 30,
                 required_chi2_decrease: float = 1e-2, max_rejects: int = 16):
        from pint_tpu.fitting import fit_auto

        self.model = model
        self.toas = toas
        self.maxiter = maxiter
        self.required_chi2_decrease = required_chi2_decrease
        self.max_rejects = max_rejects
        self.fitter = fit_auto(toas, model, fused=True)
        self.engine = None
        #: per-request SessionResult records, in arrival order
        self.history: list[SessionResult] = []

    # -- lifecycle -----------------------------------------------------------------

    def fit(self, warm_appends: int = 8) -> SessionResult:
        """Initial full fit + incremental-state capture. Idempotent: a
        refit re-runs the (warm) full fit and refreshes the blocks.
        ``warm_appends`` AOT-warms the append-serving programs at that
        append size, so the session's FIRST append is already
        steady-state (0 disables)."""
        from pint_tpu.fitting.incremental import IncrementalEngine

        t0 = time.perf_counter()
        res = self.fitter.fit_toas(
            maxiter=self.maxiter,
            required_chi2_decrease=self.required_chi2_decrease,
            max_rejects=self.max_rejects)
        if self.engine is None:
            self.engine = IncrementalEngine(self.fitter)
        else:
            self.engine.refresh(self.fitter)
        if warm_appends:
            self.engine.precompile_append(self.fitter, k_hint=warm_appends)
        out = SessionResult(res, "full", 0,
                            (time.perf_counter() - t0) * 1e3)
        self.history.append(out)
        return out

    def precompile(self, background: bool = False):
        """AOT-warm the session's full-fit programs (the incremental
        blocks/chi² programs compile on the first append of each bucket
        signature and persist in the XLA disk cache).

        With ``PINT_TPU_AOT_EXPORT=1`` this never traces in a warmed
        process: every fit/append program is an AOT-eligible
        ``TimedProgram`` (ops/compile.py ``aot_key=``), so a session
        migrated across processes — `pint_tpu warmup`, or a prior
        process of the same fleet — deserializes its executables from
        the artifact store and restores its solution from the
        ``FitterState`` snapshot (fitting/state.py): the item-3
        cross-process session migration pays disk reads, not compiles.
        ``stats()["aot"]`` reports the deserialize/compile traffic."""
        return self.fitter.precompile(background=background)

    # -- serving -------------------------------------------------------------------

    def _refit_appended(self, merged, k: int) -> "tuple":
        from pint_tpu.fitting import fit_auto

        with perf.stage("tensor"):
            fitter = fit_auto(merged, self.model, fused=True)
        ir = self.engine.refit_appended(
            fitter, k, maxiter=self.maxiter,
            required_gain=self.required_chi2_decrease,
            max_rejects=self.max_rejects)
        return fitter, ir

    def append(self, lines=None, *, utc=None, error_us=None, freq_mhz=None,
               obs=None, flags=None, refit: bool = True) -> SessionResult:
        """Ingest k new TOAs and (by default) answer the refit
        incrementally. Accepts tim ``lines`` or raw arrays
        (``TOAs.append``)."""
        if self.engine is None and refit:
            self.fit()
        t0 = time.perf_counter()
        collecting = perf.enabled()
        rep_cm = perf.collect() if collecting else None
        rep = rep_cm.__enter__() if rep_cm is not None else None
        try:
            with perf.stage("incremental"):
                with perf.stage("append"):
                    merged = self.toas.append(
                        lines, utc=utc, error_us=error_us,
                        freq_mhz=freq_mhz, obs=obs, flags=flags)
                k = len(merged) - len(self.toas)
                if refit:
                    fitter, ir = self._refit_appended(merged, k)
                    self.fitter = fitter
                self.toas = merged
        finally:
            if rep_cm is not None:
                rep_cm.__exit__(None, None, None)
        latency_ms = (time.perf_counter() - t0) * 1e3
        bd = perf.incremental_breakdown(rep) if rep is not None else None
        if not refit:
            out = SessionResult(None, "append_only", k, latency_ms,
                                breakdown=bd)
        else:
            out = SessionResult(ir.result, ir.path, k, latency_ms,
                                reason=ir.reason, breakdown=bd)
        self.history.append(out)
        return out

    # -- telemetry -----------------------------------------------------------------

    def stats(self) -> dict:
        """Per-request latency distribution + path counts — the per-chip
        serving numbers the bench's ``--smoke --session`` record carries."""
        lat = np.array([h.latency_ms for h in self.history
                        if h.path in ("incremental", "full_fallback")])
        paths: dict[str, int] = {}
        for h in self.history:
            paths[h.path] = paths.get(h.path, 0) + 1
        from pint_tpu.ops.compile import aot_block

        blk = aot_block()
        out = {
            "n_requests": len(self.history),
            "paths": paths,
            "n_toas": len(self.toas),
            # serialized-executable traffic (process-wide): a session
            # fleet warmed by `pint_tpu warmup` serves from deserialized
            # executables — hits > 0 and zero compiles on the warm path
            "aot": {"deserialize_hits": blk["deserialize_hits"],
                    "deserialize_misses": blk["deserialize_misses"],
                    "enabled": blk["enabled"]},
        }
        if lat.size:
            out.update(
                incremental_refit_ms_p50=round(float(np.percentile(lat, 50)), 3),
                incremental_refit_ms_p99=round(float(np.percentile(lat, 99)), 3),
            )
        return out


class TimingService:
    """Many resident sessions behind one request queue.

    ``submit`` enqueues ``{"session": sid, "kind": "append"|"refit",
    ...rows}`` requests; ``drain`` answers everything queued:

    - append requests for the same session coalesce into ONE prepared-
      column append + ONE rank-k refit (the batching a bursty client
      stream needs);
    - ``refit`` requests across sessions group into fleet-fit skeleton
      buckets (fitting/batch.py) and run as one fused batched program,
      after which each session's incremental state is refreshed.

    Batched ≡ sequential: the fleet driver freezes converged elements,
    so every session's answer equals serving its requests alone.
    """

    def __init__(self):
        self.sessions: dict[str, TimingSession] = {}
        self._queue: list[dict] = []

    def add_session(self, sid: str, session: TimingSession) -> None:
        if sid in self.sessions:
            raise ValueError(f"session {sid!r} already registered")
        self.sessions[sid] = session

    def submit(self, request: dict) -> None:
        sid = request.get("session")
        if sid not in self.sessions:
            raise KeyError(f"unknown session {sid!r}")
        kind = request.get("kind")
        if kind not in ("append", "refit"):
            raise ValueError(f"unknown request kind {kind!r}")
        self._queue.append(dict(request))

    def _coalesce_appends(self, reqs: list[dict]) -> dict:
        """Merge several append payloads into one row block."""
        from pint_tpu.astro import time as ptime

        eps = [r["utc"] for r in reqs]
        cat = np.concatenate
        return {
            "utc": ptime.MJDEpoch(cat([e.day for e in eps]),
                                  cat([e.frac_hi for e in eps]),
                                  cat([e.frac_lo for e in eps])),
            "error_us": cat([np.asarray(r["error_us"]) for r in reqs]),
            "freq_mhz": cat([np.asarray(r["freq_mhz"]) for r in reqs]),
            "obs": cat([np.asarray(r["obs"]) for r in reqs]),
            "flags": sum((list(r.get("flags") or
                               [{} for _ in np.asarray(r["error_us"])])
                          for r in reqs), []),
        }

    def drain(self) -> dict[str, list[SessionResult]]:
        """Answer every queued request; returns per-session results in
        submission order (coalesced/batched requests share one wall)."""
        from pint_tpu.fitting.batch import fit_batch

        queue, self._queue = self._queue, []
        out: dict[str, list[SessionResult]] = {}
        appends: dict[str, list[dict]] = {}
        refits: list[str] = []
        for r in queue:
            sid = r["session"]
            if r["kind"] == "append":
                appends.setdefault(sid, []).append(r)
            elif sid not in refits:
                refits.append(sid)
        for sid, reqs in appends.items():
            ses = self.sessions[sid]
            res = ses.append(**self._coalesce_appends(reqs))
            # every coalesced request is answered by the shared refit
            out.setdefault(sid, []).extend([res] * len(reqs))
        if refits:
            t0 = time.perf_counter()
            fitters = [self.sessions[sid].fitter for sid in refits]
            with perf.stage("incremental"), perf.stage("full_refit"):
                results = fit_batch(
                    fitters,
                    maxiter=self.sessions[refits[0]].maxiter)
            latency_ms = (time.perf_counter() - t0) * 1e3
            for sid, res in zip(refits, results):
                ses = self.sessions[sid]
                if ses.engine is None:
                    from pint_tpu.fitting.incremental import IncrementalEngine

                    ses.engine = IncrementalEngine(ses.fitter)
                else:
                    ses.engine.refresh(ses.fitter)
                sr = SessionResult(res, "full", 0, latency_ms)
                ses.history.append(sr)
                out.setdefault(sid, []).append(sr)
        return out
