"""Resident timing sessions: the warm process a timing service keeps up.

A :class:`TimingSession` owns one pulsar's prepared TOAs, its converged
downhill fitter, and the incremental-refit engine
(fitting/incremental.py). A k-TOA append is answered by the O(k)
prepared-column append (``TOAs.append``) plus the rank-k
normal-equation update — not a from-scratch prepare + fit — with
per-request latency recorded through ops/perf.py and surfaced as
p50/p99 in :meth:`TimingSession.stats`.

A :class:`TimingService` fronts many sessions: requests queue through
:meth:`~TimingService.submit` and :meth:`~TimingService.drain` answers
them — appends to the same session COALESCE into one rank-k update, and
full-refit requests across sessions batch into the fleet-fit engine's
skeleton buckets (fitting/batch.py ``fit_batch``), so B structurally
identical refits run as one fused device program. Draining is
deterministic: batched ≡ the same requests served one at a time
(locked by tests/test_session.py), because the fleet driver's masked
convergence reproduces every element's solo trajectory.

This is the synchronous substrate of the serving stack: the always-on
continuous-batching worker with admission control, load shedding and a
warm session pool is :class:`pint_tpu.serve.engine.ServingEngine` (an
async network front-end plugs into its submit/ticket surface). The
request objects are plain dicts, the latency telemetry is per-request,
and ``PINT_TPU_DEGRADED=error`` turns every silent corner-cut
(including an incremental-refit fallback) into a refusal.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

from pint_tpu.obs import trace
from pint_tpu.ops import perf
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["SessionResult", "TimingSession", "TimingService",
           "batch_refit", "coalesce_append_payloads"]

#: per-session request records retained in memory (the full latency
#: distribution lives in a bounded QuantileSketch, never in this list)
HISTORY_KEEP = 512


@dataclass
class SessionResult:
    """One answered request: the fit outcome plus its serving telemetry."""

    result: object                 # FitResult (or None for no-refit appends)
    path: str                      # "incremental" | "full_fallback" | "full" | "append_only"
    k: int                         # rows this request appended
    latency_ms: float
    reason: str | None = None      # fallback reason, when any
    breakdown: dict | None = None  # incremental_breakdown when telemetry on
    #: time this request spent queued before its (possibly shared) solve
    #: started — stamped per request, so coalesced requests carry their
    #: own wait instead of inheriting one shared wall-clock figure
    queue_ms: float | None = None


def coalesce_append_payloads(reqs: list[dict]) -> dict:
    """Merge several append payloads into one row block (submission
    order preserved — the merged rows land in the order the requests
    were queued, so coalesced ≡ sequential row-for-row)."""
    from pint_tpu.astro import time as ptime

    eps = [r["utc"] for r in reqs]
    cat = np.concatenate
    return {
        "utc": ptime.MJDEpoch(cat([e.day for e in eps]),
                              cat([e.frac_hi for e in eps]),
                              cat([e.frac_lo for e in eps])),
        "error_us": cat([np.asarray(r["error_us"]) for r in reqs]),
        "freq_mhz": cat([np.asarray(r["freq_mhz"]) for r in reqs]),
        "obs": cat([np.asarray(r["obs"]) for r in reqs]),
        "flags": sum((list(r.get("flags") or
                           [{} for _ in np.asarray(r["error_us"])])
                      for r in reqs), []),
    }


class TimingSession:
    """One pulsar's resident state: prepared TOAs + converged fitter +
    cached normal-equation blocks, answering appends incrementally.

    Construct with prepared TOAs and a model; :meth:`fit` runs the
    initial full (fused) downhill fit and captures the incremental
    state. Every :meth:`append` then prepares ONLY the new rows, updates
    the cached blocks rank-k, and polishes — falling back to a full warm
    refit (recorded on the degradation ledger) past the staleness
    bounds. The fitter kind follows ``fit_auto`` (WLS / GLS / wideband).
    """

    def __init__(self, toas, model, maxiter: int = 30,
                 required_chi2_decrease: float = 1e-2, max_rejects: int = 16):
        from pint_tpu.fitting import fit_auto

        self.model = model
        self.toas = toas
        self.maxiter = maxiter
        self.required_chi2_decrease = required_chi2_decrease
        self.max_rejects = max_rejects
        self.fitter = fit_auto(toas, model, fused=True)
        self.engine = None
        #: idempotency keys of requests already applied to this session
        #: (serve/journal.py write-ahead records carry the same keys, so
        #: crash recovery replays the journal suffix without ever
        #: double-appending; bounded — the set restarts empty at every
        #: journal-compacting fleet checkpoint, serve/recover.py)
        self.applied_idem: set[str] = set()
        #: the most recent request records, in arrival order (bounded:
        #: long-lived sessions keep the last HISTORY_KEEP only — counts
        #: and percentiles come from the bounded aggregates below)
        self.history: deque[SessionResult] = deque(maxlen=HISTORY_KEEP)
        self._n_requests = 0
        self._path_counts: dict[str, int] = {}
        #: bounded streaming latency quantiles over served refits — the
        #: same sketch the serving engine uses for its SLO telemetry,
        #: replacing the unbounded raw-sample percentile of old
        self._lat_sketch = perf.QuantileSketch()

    def _record(self, sr: SessionResult) -> SessionResult:
        """Fold one answered request into the bounded telemetry."""
        self.history.append(sr)
        self._n_requests += 1
        self._path_counts[sr.path] = self._path_counts.get(sr.path, 0) + 1
        if sr.path in ("incremental", "full_fallback"):
            self._lat_sketch.add(sr.latency_ms)
        return sr

    @classmethod
    def from_state(cls, toas, model, state, *, maxiter: int = 30,
                   required_chi2_decrease: float = 1e-2,
                   max_rejects: int = 16,
                   warm_appends: int = 8) -> "TimingSession":
        """Rebuild a resident session from a :class:`FitterState`
        snapshot WITHOUT re-running the fit: the fitter is constructed
        over the (re-)prepared TOAs, warm-started to the snapshot's
        exact (hi, lo) solution, and the incremental engine recaptures
        its blocks at that point — so the restored session's next append
        is served by the same rank-k update, from the same fixed point,
        as the session that was checkpointed (serve/pool.py evictions;
        parity locked by tests/test_serve.py). In a warmed process every
        program this touches is served by the process-global program
        caches or the ``.aotx`` artifact store: restore pays disk reads,
        not traces (``PINT_TPU_EXPECT_WARM=1`` enforces it)."""
        from pint_tpu.fitting.incremental import IncrementalEngine
        from pint_tpu.fitting.state import warm_start

        ses = cls(toas, model, maxiter=maxiter,
                  required_chi2_decrease=required_chi2_decrease,
                  max_rejects=max_rejects)
        warm_start(ses.fitter, state, strict=True)
        ses.engine = IncrementalEngine(ses.fitter)
        if warm_appends:
            ses.engine.precompile_append(ses.fitter, k_hint=warm_appends)
        return ses

    # -- lifecycle -----------------------------------------------------------------

    def fit(self, warm_appends: int = 8) -> SessionResult:
        """Initial full fit + incremental-state capture. Idempotent: a
        refit re-runs the (warm) full fit and refreshes the blocks.
        ``warm_appends`` AOT-warms the append-serving programs at that
        append size, so the session's FIRST append is already
        steady-state (0 disables)."""
        from pint_tpu.fitting.incremental import IncrementalEngine

        t0 = time.perf_counter()
        res = self.fitter.fit_toas(
            maxiter=self.maxiter,
            required_chi2_decrease=self.required_chi2_decrease,
            max_rejects=self.max_rejects)
        if self.engine is None:
            self.engine = IncrementalEngine(self.fitter)
        else:
            self.engine.refresh(self.fitter)
        if warm_appends:
            self.engine.precompile_append(self.fitter, k_hint=warm_appends)
        return self._record(SessionResult(
            res, "full", 0, (time.perf_counter() - t0) * 1e3))

    def precompile(self, background: bool = False):
        """AOT-warm the session's full-fit programs (the incremental
        blocks/chi² programs compile on the first append of each bucket
        signature and persist in the XLA disk cache).

        With ``PINT_TPU_AOT_EXPORT=1`` this never traces in a warmed
        process: every fit/append program is an AOT-eligible
        ``TimedProgram`` (ops/compile.py ``aot_key=``), so a session
        migrated across processes — `pint_tpu warmup`, or a prior
        process of the same fleet — deserializes its executables from
        the artifact store and restores its solution from the
        ``FitterState`` snapshot (fitting/state.py): the item-3
        cross-process session migration pays disk reads, not compiles.
        ``stats()["aot"]`` reports the deserialize/compile traffic."""
        return self.fitter.precompile(background=background)

    # -- serving -------------------------------------------------------------------

    def _refit_appended(self, merged, k: int) -> "tuple":
        from pint_tpu.fitting import fit_auto

        with perf.stage("tensor"):
            fitter = fit_auto(merged, self.model, fused=True)
        ir = self.engine.refit_appended(
            fitter, k, maxiter=self.maxiter,
            required_gain=self.required_chi2_decrease,
            max_rejects=self.max_rejects)
        return fitter, ir

    def append(self, lines=None, *, utc=None, error_us=None, freq_mhz=None,
               obs=None, flags=None, refit: bool = True) -> SessionResult:
        """Ingest k new TOAs and (by default) answer the refit
        incrementally. Accepts tim ``lines`` or raw arrays
        (``TOAs.append``)."""
        if self.engine is None and refit:
            self.fit()
        t0 = time.perf_counter()
        collecting = perf.enabled()
        rep_cm = perf.collect() if collecting else None
        rep = rep_cm.__enter__() if rep_cm is not None else None
        try:
            # the span joins this append to the request trace the
            # serving worker attached (a direct session.append outside
            # the engine records with trace=None — still inspectable)
            with trace.span("session.append"), perf.stage("incremental"):
                with perf.stage("append"):
                    merged = self.toas.append(
                        lines, utc=utc, error_us=error_us,
                        freq_mhz=freq_mhz, obs=obs, flags=flags)
                k = len(merged) - len(self.toas)
                if refit:
                    fitter, ir = self._refit_appended(merged, k)
                    self.fitter = fitter
                self.toas = merged
        finally:
            if rep_cm is not None:
                rep_cm.__exit__(None, None, None)
        latency_ms = (time.perf_counter() - t0) * 1e3
        bd = perf.incremental_breakdown(rep) if rep is not None else None
        if not refit:
            out = SessionResult(None, "append_only", k, latency_ms,
                                breakdown=bd)
        else:
            out = SessionResult(ir.result, ir.path, k, latency_ms,
                                reason=ir.reason, breakdown=bd)
        return self._record(out)

    # -- telemetry -----------------------------------------------------------------

    def stats(self) -> dict:
        """Per-request latency distribution + path counts — the per-chip
        serving numbers the bench's ``--smoke --session`` record carries.
        Percentiles come from the bounded :class:`~pint_tpu.ops.perf.
        QuantileSketch`, so a session serving appends for months reports
        p50/p99 from a few hundred bucket counts, not a growing sample
        list."""
        from pint_tpu.ops.compile import aot_block

        blk = aot_block()
        out = {
            "n_requests": self._n_requests,
            "paths": dict(self._path_counts),
            "n_toas": len(self.toas),
            # serialized-executable traffic (process-wide): a session
            # fleet warmed by `pint_tpu warmup` serves from deserialized
            # executables — hits > 0 and zero compiles on the warm path
            "aot": {"deserialize_hits": blk["deserialize_hits"],
                    "deserialize_misses": blk["deserialize_misses"],
                    "enabled": blk["enabled"]},
        }
        if self._lat_sketch.count:
            out.update(
                incremental_refit_ms_p50=round(
                    self._lat_sketch.quantile(0.5), 3),
                incremental_refit_ms_p99=round(
                    self._lat_sketch.quantile(0.99), 3),
            )
        return out


def batch_refit(sessions: list[TimingSession],
                maxiter: int | None = None) -> list[SessionResult]:
    """Run full refits for several resident sessions as ONE fleet-fit
    dispatch (fitting/batch.py skeleton buckets), then refresh each
    session's incremental state. Shared by :meth:`TimingService.drain`
    and the continuous-batching engine (serve/engine.py), so both answer
    batched refits identically. Returns one :class:`SessionResult` per
    session, already folded into that session's telemetry."""
    from pint_tpu.fitting.batch import fit_batch

    if not sessions:
        return []
    t0 = time.perf_counter()
    fitters = [ses.fitter for ses in sessions]
    with perf.stage("incremental"), perf.stage("full_refit"):
        results = fit_batch(
            fitters, maxiter=maxiter if maxiter is not None
            else sessions[0].maxiter)
    latency_ms = (time.perf_counter() - t0) * 1e3
    out = []
    for ses, res in zip(sessions, results):
        if ses.engine is None:
            from pint_tpu.fitting.incremental import IncrementalEngine

            ses.engine = IncrementalEngine(ses.fitter)
        else:
            ses.engine.refresh(ses.fitter)
        out.append(ses._record(SessionResult(res, "full", 0, latency_ms)))
    return out


class TimingService:
    """Many resident sessions behind one request queue.

    ``submit`` enqueues ``{"session": sid, "kind": "append"|"refit",
    ...rows}`` requests (thread-safe: concurrent client threads submit
    into one queue, each request stamped with its own enqueue time);
    ``drain`` answers everything queued:

    - append requests for the same session coalesce into ONE prepared-
      column append + ONE rank-k refit (the batching a bursty client
      stream needs);
    - ``refit`` requests across sessions group into fleet-fit skeleton
      buckets (fitting/batch.py) and run as one fused batched program,
      after which each session's incremental state is refreshed.

    Batched ≡ sequential: the fleet driver freezes converged elements,
    so every session's answer equals serving its requests alone. Every
    returned :class:`SessionResult` carries PER-REQUEST latency —
    ``latency_ms`` measured from that request's own enqueue stamp and
    ``queue_ms`` for the wait before its (possibly shared) solve — never
    one wall-clock figure smeared over a coalesced batch.

    This is the synchronous substrate; the always-on worker loop with
    admission control and deadline-driven dispatch is
    :class:`pint_tpu.serve.engine.ServingEngine`.
    """

    def __init__(self):
        self.sessions: dict[str, TimingSession] = {}
        self._queue: list[dict] = []
        self._lock = threading.Lock()

    def add_session(self, sid: str, session: TimingSession) -> None:
        if sid in self.sessions:
            raise ValueError(f"session {sid!r} already registered")
        self.sessions[sid] = session

    def submit(self, request: dict) -> None:
        sid = request.get("session")
        if sid not in self.sessions:
            raise KeyError(f"unknown session {sid!r}")
        kind = request.get("kind")
        if kind not in ("append", "refit"):
            raise ValueError(f"unknown request kind {kind!r}")
        request = dict(request)
        # per-request enqueue stamp: queue wait is attributed to THIS
        # request even when a coalesced batch answers it
        request["_enqueue_t"] = time.perf_counter()
        with self._lock:
            self._queue.append(request)

    def _coalesce_appends(self, reqs: list[dict]) -> dict:
        """Merge several append payloads into one row block."""
        return coalesce_append_payloads(reqs)

    @staticmethod
    def _per_request(reqs: list[dict], shared: SessionResult,
                     t_dispatch: float, t_done: float) -> list[SessionResult]:
        """Wrap one shared solve into per-request results: each request
        carries its own queue wait + end-to-end latency and its own row
        count; the FitResult/breakdown of the shared solve is shared."""
        out = []
        for r in reqs:
            t_enq = r.get("_enqueue_t", t_dispatch)
            k = (len(np.asarray(r["error_us"]))
                 if r.get("error_us") is not None else shared.k)
            out.append(SessionResult(
                shared.result, shared.path, k,
                latency_ms=(t_done - t_enq) * 1e3,
                reason=shared.reason, breakdown=shared.breakdown,
                queue_ms=max(t_dispatch - t_enq, 0.0) * 1e3))
        return out

    def drain(self) -> dict[str, list[SessionResult]]:
        """Answer every queued request; returns per-session results in
        submission order (coalesced/batched requests share one solve but
        report their own latencies)."""
        with self._lock:
            queue, self._queue = self._queue, []
        out: dict[str, list[SessionResult]] = {}
        appends: dict[str, list[dict]] = {}
        refits: dict[str, list[dict]] = {}
        for r in queue:
            sid = r["session"]
            if r["kind"] == "append":
                appends.setdefault(sid, []).append(r)
            else:
                refits.setdefault(sid, []).append(r)
        for sid, reqs in appends.items():
            ses = self.sessions[sid]
            t_dispatch = time.perf_counter()
            res = ses.append(**self._coalesce_appends(reqs))
            t_done = time.perf_counter()
            out.setdefault(sid, []).extend(
                self._per_request(reqs, res, t_dispatch, t_done))
        if refits:
            t_dispatch = time.perf_counter()
            sids = list(refits)
            results = batch_refit([self.sessions[sid] for sid in sids])
            t_done = time.perf_counter()
            for sid, sr in zip(sids, results):
                out.setdefault(sid, []).extend(
                    self._per_request(refits[sid], sr, t_dispatch, t_done))
        return out
