"""Replicated serving fleet: R engine worker processes, shared warm caches.

One serving process is bounded by one dispatcher and one warm pool;
horizontal scale-out runs R of them (:class:`ReplicaFleet`), each a
plain ``python -m pint_tpu.serve.fleet --replica`` worker that

- shares the content-addressed stores through ``PINT_TPU_CACHE_DIR`` —
  the ``.aotx`` serialized-executable artifacts, the prepared-TOA disk
  cache, the ephemeris kernel packs and the persistent XLA cache are
  all keyed by content, so replica #2 starting into a warmed cache root
  compiles NOTHING (``traces_on_warm == 0``, the bench's second-replica
  bar);
- owns a durable directory (checkpoints + write-ahead journal,
  serve/recover.py) it recovers from at startup and journals into while
  serving — which doubles as the migration/absorb handoff source: the
  durable layout IS the handoff layout;
- serves its HTTP surface through a :class:`~pint_tpu.serve.gateway.
  Gateway` and reports ``READY::{json}`` on stdout once recovered.

Placement is rendezvous hashing (serve/route.py): the parent stages
each session's checkpoint into its owner replica's durable dir before
spawning, every router recomputes the same owner, and adding a replica
moves ~1/R of the sessions. The :class:`~pint_tpu.serve.gateway.
FleetGateway` fronts the fleet (routing, pins, merged telemetry,
migrate/absorb control).

Chaos drill (``bench.py --smoke --fleet``): arm ``serve.crash:exit`` in
a replica via its ``/v1/fault`` endpoint, submit — the replica dies
mid-dispatch with exit code 70 (admitted + journaled, not applied) —
then ``FleetGateway.absorb`` moves its sessions onto the survivors from
the durable store with ``requests_lost == 0`` and ``serve.replica_lost``
on the ledger.
"""

from __future__ import annotations

import json
import os
import select
import subprocess
import sys
import time
from pathlib import Path

from pint_tpu.ops import degrade
from pint_tpu.serve import route
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["ReplicaFleet"]

_READY = "READY::"


class ReplicaFleet:
    """Spawn, stage and supervise R replica worker processes (see module
    docstring). The parent stays a pure controller: it writes staging
    checkpoints, launches workers, and talks HTTP afterwards."""

    def __init__(self, root: str | Path, names: list[str] | None = None):
        self.root = Path(root)
        if names is None:
            n = int(knobs.get("PINT_TPU_FLEET_REPLICAS"))
            names = [f"r{i}" for i in range(n)]
        self.names = list(names)
        #: name -> {"proc": Popen|None, "port": int, "ready": dict}
        self.procs: dict[str, dict] = {}

    def dir_for(self, name: str) -> Path:
        return self.root / f"replica-{name}"

    # -- staging -----------------------------------------------------------------

    def stage_session(self, sid: str, session) -> str:
        """Write ``sid``'s checkpoint into its rendezvous owner's
        durable dir (the worker recovers it warm at startup). Returns
        the owner's name."""
        from pint_tpu.serve.pool import SessionCheckpoint
        from pint_tpu.serve.recover import _write_checkpoint

        name = route.owner(sid, self.names)
        sdir = self.dir_for(name) / "sessions"
        sdir.mkdir(parents=True, exist_ok=True)
        _write_checkpoint(sdir / f"{sid}.ckpt",
                          SessionCheckpoint.capture(session))
        return name

    # -- process supervision -----------------------------------------------------

    def spawn(self, name: str, extra_env: dict | None = None,
              timeout_s: float | None = None) -> dict:
        """Launch one replica worker and block until its ``READY::``
        line (recovery + gateway bind are done). Returns the ready
        report (port, sessions, traces_on_warm, ...).

        The handshake is bounded by ``timeout_s`` (default
        ``PINT_TPU_FLEET_READY_TIMEOUT_S``) with a non-blocking read
        loop: a worker that HANGS before its handshake (deadlocked
        recovery, wedged device init) — not just one that dies — is
        reaped at the deadline instead of blocking the fleet start
        forever. Both shapes raise RuntimeError; :meth:`spawn_all`
        converts that into a degraded R−1 start."""
        if timeout_s is None:
            timeout_s = float(
                knobs.get("PINT_TPU_FLEET_READY_TIMEOUT_S"))
        d = self.dir_for(name)
        d.mkdir(parents=True, exist_ok=True)
        env = dict(os.environ)  # jaxlint: disable=env-read — the worker must inherit the parent's knob/cache environment verbatim
        env.update(extra_env or {})
        proc = subprocess.Popen(
            [sys.executable, "-m", "pint_tpu.serve.fleet", "--replica",
             "--dir", str(d), "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        deadline = time.monotonic() + timeout_s
        ready = None
        died = False
        assert proc.stdout is not None
        # raw-fd read loop: readline() on the pipe would block past the
        # deadline on a hung worker — select + os.read keeps the budget
        fd = proc.stdout.fileno()
        buf = b""
        while time.monotonic() < deadline and ready is None:
            r, _, _ = select.select(
                [fd], [], [], min(0.2, max(deadline - time.monotonic(),
                                           0.01)))
            if r:
                chunk = os.read(fd, 65536)
                if not chunk:
                    died = True        # EOF: the worker died pre-ready
                    break
                buf += chunk
                *lines, buf = buf.split(b"\n")
                for line in lines:
                    text = line.decode(errors="replace")
                    if text.startswith(_READY):
                        ready = json.loads(text[len(_READY):])
                        break
            elif proc.poll() is not None:
                died = True
                break
        if ready is None:
            proc.kill()
            try:
                _, err = proc.communicate(timeout=10.0)
            except subprocess.TimeoutExpired:
                err = ""
            shape = ("died before" if died else
                     f"hung past the {timeout_s:.0f}s handshake budget "
                     "(PINT_TPU_FLEET_READY_TIMEOUT_S) before")
            raise RuntimeError(
                f"replica {name!r} {shape} its READY:: handshake: "
                f"{(err or '')[-2000:]}")
        self.procs[name] = {"proc": proc, "port": ready["port"],
                            "ready": ready}
        log.info(f"replica {name!r} ready on port {ready['port']} "
                 f"({ready['sessions']} session(s), "
                 f"{ready['traces_on_warm']} traces)")
        return ready

    def spawn_all(self, extra_env: dict | None = None,
                  per_replica_env: dict | None = None) -> dict:
        """Spawn every replica; one that dies or hangs before its
        handshake is reaped and recorded as ``serve.replica_lost``
        (refusable under ``PINT_TPU_DEGRADED=error``) and the fleet
        STARTS DEGRADED at R−1 — the lost name leaves ``self.names`` so
        rendezvous routing covers only live replicas. Sessions staged
        into the lost replica's durable dir are absorbable later
        (``FleetGateway.absorb``). Raises only when NO replica reports
        ready. ``per_replica_env`` layers name-keyed env overrides on
        top of ``extra_env`` (chaos drills poison one worker)."""
        out: dict = {}
        total = len(self.names)
        for name in list(self.names):
            env = dict(extra_env or {})
            env.update((per_replica_env or {}).get(name, {}))
            try:
                out[name] = self.spawn(name, env)
            except RuntimeError as e:
                self.names.remove(name)
                degrade.record(
                    "serve.replica_lost", f"replica:{name}",
                    f"replica {name!r} failed its READY:: handshake "
                    f"({e}); the fleet starts degraded at "
                    f"{len(self.names)} of {total} replicas",
                    fix="raise PINT_TPU_FLEET_READY_TIMEOUT_S or inspect "
                        "the replica's stderr and durable dir; absorb its "
                        "staged sessions or re-spawn it once fixed")
        if not out:
            raise RuntimeError(
                f"no replica of {total} reported ready; fleet start "
                "refused")
        return out

    def url(self, name: str) -> str:
        return f"http://127.0.0.1:{self.procs[name]['port']}"

    def gateway(self, handoff_root: str | Path | None = None):
        """A :class:`~pint_tpu.serve.gateway.FleetGateway` fronting every
        spawned replica (handoff_root defaults under the fleet root)."""
        from pint_tpu.serve.gateway import FleetGateway

        fg = FleetGateway(handoff_root=self.root / "handoff"
                          if handoff_root is None else handoff_root)
        for name in self.procs:
            fg.add_replica(name, self.url(name),
                           durable_dir=self.dir_for(name))
        return fg

    def wait_exit(self, name: str, timeout_s: float = 120.0) -> int:
        """Block until a replica process exits; returns its returncode
        (70 = the ``serve.crash:exit`` chaos drill fired)."""
        proc = self.procs[name]["proc"]
        rc = proc.wait(timeout=timeout_s)
        return rc

    def stop_all(self, drain: bool = True, timeout_s: float = 120.0):
        """Stop every live replica through its ``/v1/stop`` endpoint
        (drain flushes + checkpoints + closes the journal clean), then
        reap the processes."""
        from pint_tpu.serve.gateway import http_json

        for name, info in list(self.procs.items()):
            proc = info["proc"]
            if proc.poll() is not None:
                continue
            try:
                http_json(self.url(name) + "/v1/stop", {"drain": drain},
                          timeout=timeout_s)
            except OSError:
                pass
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)


def _replica_main(argv: list[str] | None = None) -> int:
    """The worker entrypoint (``python -m pint_tpu.serve.fleet
    --replica --dir D --port P``): recover the durable dir into a live
    engine (warm via the shared caches — the READY report carries
    ``traces_on_warm`` so the bench can lock it at 0), start serving,
    bind the gateway, report ready, and wait for ``/v1/stop``."""
    import argparse

    ap = argparse.ArgumentParser(prog="pint_tpu.serve.fleet")
    ap.add_argument("--replica", action="store_true")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--port", type=int, default=None)
    args = ap.parse_args(argv)

    from pint_tpu.testing import faults

    # the startup-robustness drill (serve.ready site): "hang" wedges the
    # worker before its handshake — the parent's READY timeout must reap
    # it; "exit" dies before the handshake — either way the fleet starts
    # degraded at R−1 with serve.replica_lost on the ledger
    mode = faults.trip("serve.ready", f"dir:{args.dir}")
    if mode == "hang":
        time.sleep(3600.0)
    elif mode == "exit":
        return 70

    from pint_tpu.ops.compile import setup_persistent_cache

    setup_persistent_cache()
    from pint_tpu.analysis.jaxpr_audit import compile_count
    from pint_tpu.serve.gateway import Gateway
    from pint_tpu.serve.recover import recover_fleet

    c0 = compile_count()
    engine, report = recover_fleet(args.dir)
    traces = compile_count() - c0
    engine.start()
    gw = Gateway(engine, port=args.port)
    port = gw.start()
    print(_READY + json.dumps({
        "port": port,
        "pid": os.getpid(),
        "dir": args.dir,
        "sessions": report["sessions"],
        "traces_on_warm": traces,
        "replayed": report["replayed"],
        "deduped": report["deduped"],
        "requests_lost": report["requests_lost"],
        "recovery_time_s": report["recovery_time_s"],
    }), flush=True)
    gw.stopped.wait()
    return 0


if __name__ == "__main__":
    sys.exit(_replica_main())
