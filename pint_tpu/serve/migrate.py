"""Live session migration: checkpoint + journal-suffix handoff.

A replicated fleet (serve/fleet.py) rebalances by MOVING a warm session
between replicas without losing a single admitted request. The handoff
is assembled from the proven durability pieces (ISSUE 14, serve/
recover.py + serve/journal.py) — nothing here invents a new encoding:

- **Export** (:func:`export_session`, on the source replica): under the
  session's restore/evict mutex, capture a
  :class:`~pint_tpu.serve.pool.SessionCheckpoint` (exact ``FitterState``
  solution + raw TOA rows + the idempotency keys already applied) into
  ``<handoff>/sessions/<sid>.ckpt`` (crc32-framed, atomic), copy the
  session's post-checkpoint journal suffix into ``<handoff>/journal/``
  as ordinary framed journal records, then forget the session — the
  source no longer owns it. A ``migrate_out`` marker in the source
  journal makes the ownership transfer itself durable: a source crash
  after the handoff does not count the moved session's old records as
  lost.
- **Import** (:func:`import_session`, on the target replica): restore
  the checkpoint into the warm pool (zero traces in a warmed shared-
  cache environment — the whole point of migrating instead of cold-
  starting), then replay the handoff journal suffix with
  idempotency-key dedup: a request that landed in the checkpoint AND
  survives in the suffix is applied exactly once. The report locks
  ``requests_lost == 0``.

Every migration is a ledger-visible ``serve.migrate`` degradation
(ops/degrade.py) — the session paused for the handoff — refusable under
``PINT_TPU_DEGRADED=error`` and drillable end-to-end via the
``serve.migrate:force`` fault site. ``PINT_TPU_MIGRATE_TIMEOUT_S``
bounds the whole handoff; past it :class:`MigrateError` is raised and
the fleet keeps the session where it was rather than stalling.
"""

from __future__ import annotations

import json
import time
import zlib
from pathlib import Path

from pint_tpu.obs import flight
from pint_tpu.ops import degrade, perf
from pint_tpu.serve.journal import _FRAME, replay_records


def _read_live_records(journal) -> list[dict]:
    """Every whole post-checkpoint record in a LIVE journal, read under
    its lock (so no writer is mid-frame) and WITHOUT the mutating repair
    steps :func:`replay_records` applies to a dead one — truncating a
    live segment under an open appending handle would eat a record."""
    records: list[dict] = []
    with journal._lock:
        journal._fh.flush()
        for seg in journal.segments():
            data = seg.read_bytes()
            off = 0
            while off + _FRAME.size <= len(data):
                length, crc = _FRAME.unpack_from(data, off)
                payload = data[off + _FRAME.size:
                               off + _FRAME.size + length]
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                try:
                    records.append(json.loads(payload))
                except ValueError:
                    break
                off += _FRAME.size + length
    records.sort(key=lambda r: r.get("seq", 0))
    last_ck = max((i for i, r in enumerate(records)
                   if r.get("op") == "checkpoint"), default=-1)
    return records[last_ck + 1:]
from pint_tpu.serve.recover import _read_checkpoint, _write_checkpoint
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["MigrateError", "export_session", "import_session",
           "migrate_session"]


class MigrateError(RuntimeError):
    """The handoff could not complete (timeout, missing session, corrupt
    handoff dir); the session stays where it last was — migration fails
    closed, it never halves a session between replicas."""


def _handoff_paths(handoff_dir: str | Path) -> tuple[Path, Path]:
    root = Path(handoff_dir)
    return root / "sessions", root / "journal"


def export_session(engine, sid: str, handoff_dir: str | Path) -> dict:
    """Capture ``sid`` from ``engine`` into a durable handoff directory
    and release ownership (see module docstring). Returns the export
    report: ``{"sid", "n_toas", "suffix_records", "export_s"}``.

    The per-session mutex is held for the whole capture, so the
    checkpoint can never freeze a half-applied append; the engine keeps
    serving every OTHER session meanwhile."""
    from pint_tpu.serve.pool import SessionCheckpoint

    t0 = time.perf_counter()
    sdir, jdir = _handoff_paths(handoff_dir)
    sdir.mkdir(parents=True, exist_ok=True)
    jdir.mkdir(parents=True, exist_ok=True)
    pool = engine.pool
    with perf.stage("serve"), perf.stage("checkpoint"), \
            pool.session_lock(sid):
        with pool._lock:
            ses = pool._live.get(sid)
            ck = (SessionCheckpoint.capture(ses) if ses is not None
                  else pool._checkpoints.get(sid))
        if ck is None:
            raise MigrateError(f"unknown session {sid!r}; nothing to "
                               "export")
        _write_checkpoint(sdir / f"{sid}.ckpt", ck)
        # the session's post-checkpoint journal suffix rides along as
        # ordinary framed records: the target replays them through the
        # same idempotency dedup recovery uses — requests the checkpoint
        # already captured are skipped, the rest apply exactly once
        suffix = []
        if engine.journal is not None:
            suffix = [r for r in _read_live_records(engine.journal)
                      if r.get("op") == "request"
                      and r.get("session") == sid]
            with open(jdir / "journal-000001.wal", "ab") as fh:
                for rec in suffix:
                    payload = json.dumps(
                        rec, separators=(",", ":")).encode()
                    fh.write(_FRAME.pack(len(payload),
                                         zlib.crc32(payload)))
                    fh.write(payload)
                fh.flush()
            # durable ownership transfer: a source crash after this
            # marker must not count the moved session's records as lost
            engine.journal.mark("migrate_out", sid=sid)
        pool.remove(sid)
        if engine.durable_dir is not None:
            # the source's own durable store forgets the session too: a
            # later source recovery must not resurrect a moved session
            own = Path(engine.durable_dir) / "sessions" / f"{sid}.ckpt"
            own.unlink(missing_ok=True)
    report = {
        "sid": sid,
        "n_toas": ck.n_toas,
        "suffix_records": len(suffix),
        "export_s": round(time.perf_counter() - t0, 4),
    }
    flight.note("migrate.export", session=sid, n_toas=ck.n_toas,
                suffix=len(suffix))
    log.info(f"exported session {sid!r} for migration "
             f"({ck.n_toas} TOAs, {len(suffix)} suffix record(s))")
    return report


def import_session(engine, handoff_dir: str | Path,
                   sid: str | None = None) -> dict:
    """Restore a handed-off session into ``engine`` and replay its
    journal suffix with idempotency dedup (see module docstring).
    ``sid=None`` imports every session in the handoff directory.
    Returns ``{"sids", "replayed", "deduped", "requests_lost",
    "import_s"}`` — the migration contract locks ``requests_lost`` at 0.
    """
    from pint_tpu.serve.journal import decode_rows

    t0 = time.perf_counter()
    sdir, jdir = _handoff_paths(handoff_dir)
    paths = ([sdir / f"{sid}.ckpt"] if sid is not None
             else sorted(sdir.glob("*.ckpt")))
    if not paths or not all(p.exists() for p in paths):
        raise MigrateError(
            f"handoff directory {handoff_dir} carries no checkpoint for "
            f"{sid if sid is not None else 'any session'!r}")
    pool = engine.pool
    sids: list[str] = []
    with perf.stage("serve"), perf.stage("recover"):
        for path in paths:
            ck = _read_checkpoint(path)
            with pool.session_lock(path.stem):
                pool.put(path.stem, ck.restore())
                pool.restores += 1
            sids.append(path.stem)
    replayed = deduped = lost = 0
    records, _ = (replay_records(jdir) if jdir.exists() else ([], None))
    with perf.stage("serve"), perf.stage("replay"):
        for rec in records:
            if rec.get("op") != "request" or rec["session"] not in sids:
                continue
            ses = pool.get(rec["session"])
            if rec.get("idem") in ses.applied_idem:
                deduped += 1           # already inside the checkpoint
                continue
            if rec["kind"] == "append":
                ses.append(**decode_rows(rec["rows"]))
            else:
                from pint_tpu.serve.session import batch_refit

                batch_refit([ses], maxiter=engine.maxiter)
            if rec.get("idem"):
                ses.applied_idem.add(rec["idem"])
            replayed += 1
    # the target now owns the sessions durably: checkpoint them into its
    # OWN store (and mark the journal) so a target crash right after the
    # handoff still recovers them
    if engine.durable_dir is not None:
        own = Path(engine.durable_dir) / "sessions"
        own.mkdir(parents=True, exist_ok=True)
        from pint_tpu.serve.pool import SessionCheckpoint

        for s in sids:
            with pool.session_lock(s):
                _write_checkpoint(own / f"{s}.ckpt",
                                  SessionCheckpoint.capture(pool.get(s)))
    if engine.journal is not None:
        for s in sids:
            engine.journal.mark("migrate_in", sid=s)
    for s in sids:
        perf.add("serve_migrations")
        degrade.record(
            "serve.migrate", f"session:{s}",
            f"session {s!r} live-migrated onto this replica (checkpoint "
            f"+ {replayed} journal-suffix record(s) replayed, {deduped} "
            "deduped); the session paused for the handoff, no request "
            "was lost",
            bound_us=0.0,              # accuracy preserved; a pause, not an error
            fix="none needed — rebalancing is routine; raise "
                "PINT_TPU_MIGRATE_TIMEOUT_S if handoffs time out")
    report = {
        "sids": sids,
        "replayed": replayed,
        "deduped": deduped,
        "requests_lost": lost,
        "import_s": round(time.perf_counter() - t0, 4),
    }
    flight.note("migrate.import", sessions=len(sids), replayed=replayed,
                deduped=deduped)
    log.info(f"imported migrated session(s) {sids}: {replayed} "
             f"replayed, {deduped} deduped, {lost} lost")
    return report


def migrate_session(src, dst, sid: str,
                    handoff_dir: str | Path) -> dict:
    """One-call in-process migration: export from ``src``, import into
    ``dst``, bounded by ``PINT_TPU_MIGRATE_TIMEOUT_S``. Returns the
    merged report (export + import keys). The fleet's cross-process path
    drives the same two halves over HTTP (serve/gateway.py)."""
    budget = float(knobs.get("PINT_TPU_MIGRATE_TIMEOUT_S"))
    t0 = time.perf_counter()
    out = export_session(src, sid, handoff_dir)
    if time.perf_counter() - t0 > budget:
        raise MigrateError(
            f"migration of {sid!r} blew its {budget:.0f}s budget during "
            "export; the handoff checkpoint is durable — re-import it "
            "explicitly or raise PINT_TPU_MIGRATE_TIMEOUT_S")
    out.update(import_session(dst, handoff_dir, sid=sid))
    out["migrate_s"] = round(time.perf_counter() - t0, 4)
    return out
