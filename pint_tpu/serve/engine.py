"""The serving throughput engine: an always-on worker over TimingService
primitives with continuous batching, a warm pool, and admission control.

PR 10 built the physics of serving — O(k) appends, rank-k refits,
fleet-batched full fits — behind a synchronous ``drain()``. This module
is the part that makes it a *service*: a worker loop that keeps the
device saturated with batched likelihood work (the Vela.jl lesson,
arXiv:2412.15858) while bounding what any single client experiences.

The life of a request::

    client thread                      worker thread
    -------------                      -------------
    submit() ──admit──▶ lane  ──due──▶ coalesce ─▶ dispatch ─▶ solve ─▶ finalize
       │        │                                   (pool.get,   (rank-k /    │
       │     ShedError                               restore)    fit_batch)   │
       ▼                                                                      ▼
    ticket.wait() ◀──────────────────────────── result + per-request stamps ──┘

- **submit** admits (bounded queue, per-tenant token buckets,
  ``serve.shed`` on overload — scheduler.py) and queues the request into
  its lane: per-session for appends, per-(fit-kind, row-bucket) skeleton
  class for refits. Returns a :class:`ServeTicket` immediately.
- **the worker** dispatches a lane the moment it fills or its oldest
  request hits the live deadline (base ``PINT_TPU_SERVE_MAX_WAIT_MS``,
  stretched when recent dispatches wasted padding, collapsed under
  queue pressure). Same-session appends coalesce into ONE rank-k
  update; refit lanes run through the fleet engine as one batched
  program (session.py ``batch_refit``). Sessions come from the warm
  :class:`~pint_tpu.serve.pool.SessionPool` (LRU + checkpoint/restore).
- **telemetry**: every stage records into the ``serve`` perf tree
  (``ops/perf.py serve_breakdown``, ≥90% attribution contract) and
  every request feeds bounded :class:`~pint_tpu.ops.perf.QuantileSketch`
  latency/queue-wait distributions — the p50/p99 a replayed-trace bench
  (``python bench.py --smoke --serve``) reports as
  ``serve_p50_ms``/``serve_p99_ms``.

Run modes: :meth:`ServingEngine.start` spawns the resident worker
thread (the always-on shape — `stop()` drains it); for deterministic
tests and synchronous callers, :meth:`run_until_idle` serves the
current queue to completion on the calling thread with identical code
paths.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from pint_tpu.ops import perf
from pint_tpu.serve.pool import SessionPool
from pint_tpu.serve.scheduler import (AdmissionController,
                                      ContinuousBatchScheduler, Lane,
                                      ShedError)
from pint_tpu.serve.session import (SessionResult, batch_refit,
                                    coalesce_append_payloads)
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["ServeTicket", "ServingEngine"]


@dataclass
class ServeTicket:
    """One admitted request's handle: completion event, result slot and
    the per-request SLO stamps (submit → dispatch → done)."""

    session: str
    kind: str                      # "append" | "refit"
    tenant: str
    rows: int                      # payload rows (appends; 1 for refits)
    lane_key: tuple
    payload: dict | None = None
    t_submit: float = 0.0
    t_dispatch: float | None = None
    t_done: float | None = None
    result: SessionResult | None = None
    error: BaseException | None = None
    _event: threading.Event = field(default_factory=threading.Event)

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> SessionResult:
        """Block until served; raises the shed/solve error when the
        request failed, returns its :class:`SessionResult` otherwise."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request for session {self.session!r} not served within "
                f"{timeout} s")
        if self.error is not None:
            raise self.error
        return self.result

    @property
    def latency_ms(self) -> float | None:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3

    @property
    def queue_ms(self) -> float | None:
        if self.t_dispatch is None:
            return None
        return (self.t_dispatch - self.t_submit) * 1e3


class ServingEngine:
    """Continuous-batching serving engine over a warm session pool (see
    module docstring). Constructor knobs default from the registry
    (``PINT_TPU_SERVE_*``); explicit arguments override for tests."""

    def __init__(self, pool: SessionPool | None = None, *,
                 max_wait_ms: float | None = None,
                 queue_depth: int | None = None,
                 tenant_rps: float | None = None,
                 shed_policy: str | None = None,
                 coalesce_rows: int = 16, refit_batch: int = 4,
                 maxiter: int = 30, clock=time.monotonic):
        self.pool = pool if pool is not None else SessionPool()
        self.admission = AdmissionController(
            max_depth=queue_depth, tenant_rps=tenant_rps,
            policy=shed_policy, clock=clock)
        self.scheduler = ContinuousBatchScheduler(
            max_wait_ms=max_wait_ms, coalesce_rows=coalesce_rows,
            refit_batch=refit_batch, clock=clock)
        self.maxiter = maxiter
        self._clock = clock
        self._cv = threading.Condition()
        self._stopping = False
        self._thread: threading.Thread | None = None
        # served-request SLO sketches: bounded memory at any uptime;
        # appends and refits keep separate latency distributions (a
        # full-refit wall would otherwise smear the append p99 the SLO
        # actually bounds)
        self.latency = perf.QuantileSketch()
        self.refit_latency = perf.QuantileSketch()
        self.queue_wait = perf.QuantileSketch()
        self.served = 0
        self.dispatches = 0

    # -- sessions --------------------------------------------------------------------

    def add_session(self, sid: str, session) -> None:
        """Register a fitted resident session under ``sid``."""
        self.pool.put(sid, session)

    def _lane_key(self, sid: str, kind: str) -> tuple:
        if kind == "append":
            return ("append", sid)
        # refits batch across sessions sharing a fleet skeleton class:
        # group by fit kind + padded row bucket so one lane fills one
        # fixed-shape batched program (fitting/batch.py buckets further
        # by exact skeleton — a mixed lane still dispatches correctly,
        # it just fans into more than one bucket)
        from pint_tpu.fitting.incremental import (MIN_APPEND_BUCKET,
                                                  _pow2_at_least)

        ses = self.pool.get(sid)
        bucket = _pow2_at_least(len(ses.toas), MIN_APPEND_BUCKET)
        return ("refit", ses.fitter._fused_kind, bucket)

    def _append_cap(self, sid: str) -> int:
        """Max rows one coalesced dispatch may append and stay inside
        the incremental staleness envelope (PINT_TPU_INCR_MAX_FRAC)."""
        try:
            n = len(self.pool.get(sid).toas)
        except KeyError:
            return self.scheduler.coalesce_rows
        frac = float(knobs.get("PINT_TPU_INCR_MAX_FRAC"))
        return max(1, int(frac * n))

    # -- intake ----------------------------------------------------------------------

    def submit(self, *, session: str, kind: str = "append",
               tenant: str = "default", utc=None, error_us=None,
               freq_mhz=None, obs=None, flags=None) -> ServeTicket:
        """Admit one request and queue it for the worker; returns its
        :class:`ServeTicket`. Sheds raise :class:`ShedError` (or
        ``DegradedError`` under ``PINT_TPU_DEGRADED=error``) here, at
        the client — overload is an explicit refusal, not a timeout."""
        if kind not in ("append", "refit"):
            raise ValueError(f"unknown request kind {kind!r}")
        if session not in self.pool:
            raise KeyError(f"unknown session {session!r}")
        payload = None
        rows = 1
        if kind == "append":
            payload = dict(utc=utc, error_us=error_us, freq_mhz=freq_mhz,
                           obs=obs, flags=flags)
            rows = len(np.asarray(error_us))
        with perf.stage("serve"):
            with perf.stage("admit"):
                action = self.admission.admit(tenant,
                                              self.scheduler.depth())
                if action == "drop_oldest":
                    victim = self.scheduler.drop_oldest()
                    if victim is not None:
                        self.admission.record_drop(
                            victim.tenant,
                            f"request from tenant {victim.tenant!r} for "
                            f"session {victim.session!r} dropped to admit "
                            "newer work (PINT_TPU_SERVE_SHED_POLICY="
                            "drop_oldest)")
                        victim.error = ShedError(
                            "dropped by a newer request under "
                            "drop_oldest shed policy")
                        victim.t_done = self._clock()
                        victim._event.set()
                ticket = ServeTicket(
                    session=session, kind=kind, tenant=tenant, rows=rows,
                    lane_key=self._lane_key(session, kind),
                    payload=payload, t_submit=self._clock())
                perf.add("serve_requests")
                self.scheduler.offer(ticket, rows=rows)
        with self._cv:
            self._cv.notify()
        return ticket

    # -- the worker ------------------------------------------------------------------

    def _dispatch_append(self, batch: Lane) -> None:
        with perf.stage("dispatch"):
            session = self.pool.get(batch.sid)
        with perf.stage("coalesce"):
            merged = coalesce_append_payloads(
                [t.payload for t in batch.tickets])
            if len(batch.tickets) > 1:
                perf.add("serve_coalesced", len(batch.tickets))
        with perf.stage("solve"):
            shared = session.append(**merged)
        self._finalize(batch, shared,
                       waste=1.0 - batch.rows / self._append_bucket(
                           batch.rows))
        perf.add("serve_appends", len(batch.tickets))

    @staticmethod
    def _append_bucket(rows: int) -> int:
        from pint_tpu.fitting.incremental import append_bucket

        return append_bucket(rows)

    def _dispatch_refit(self, batch: Lane) -> None:
        # one ticket per (session, request); a session refits ONCE per
        # dispatch — duplicate refit requests share the solve
        sids: list[str] = []
        for t in batch.tickets:
            if t.session not in sids:
                sids.append(t.session)
        with perf.stage("dispatch"):
            sessions = [self.pool.get(sid) for sid in sids]
        with perf.stage("solve"), perf.collect() as rep:
            results = batch_refit(sessions, maxiter=self.maxiter)
        by_sid = dict(zip(sids, results))
        self._finalize(batch, None, by_sid=by_sid,
                       waste=rep.values.get("padding_waste_frac"))
        perf.add("serve_refits", len(batch.tickets))

    def _finalize(self, batch: Lane, shared: SessionResult | None,
                  by_sid: dict | None = None,
                  waste: float | None = None) -> None:
        with perf.stage("finalize"):
            now = self._clock()
            for t in batch.tickets:
                base = shared if shared is not None else by_sid[t.session]
                t.t_dispatch = t.t_dispatch or batch.t_oldest
                t.t_done = now
                t.result = SessionResult(
                    base.result, base.path, t.rows if t.kind == "append"
                    else 0,
                    latency_ms=(now - t.t_submit) * 1e3,
                    reason=base.reason, breakdown=base.breakdown,
                    queue_ms=max(t.t_dispatch - t.t_submit, 0.0) * 1e3)
                (self.latency if t.kind == "append"
                 else self.refit_latency).add(t.result.latency_ms)
                self.queue_wait.add(t.result.queue_ms)
                self.served += 1
                t._event.set()
            self.dispatches += 1
            perf.add("serve_dispatches")
            self.scheduler.observe_waste(waste)

    def _dispatch(self, batch: Lane) -> None:
        t_d = self._clock()
        for t in batch.tickets:
            t.t_dispatch = t_d
        try:
            if batch.kind == "append":
                self._dispatch_append(batch)
            else:
                self._dispatch_refit(batch)
        except BaseException as e:  # noqa: BLE001 — the failure is DELIVERED to every waiting client ticket (and re-raised to synchronous callers); nothing is swallowed  # jaxlint: disable=silent-except
            now = self._clock()
            for t in batch.tickets:
                if not t._event.is_set():
                    t.error = e
                    t.t_done = now
                    t._event.set()
            if not isinstance(e, Exception):
                raise

    def step(self, wait_s: float = 0.0) -> int:
        """One worker turn: (optionally) wait for work or the earliest
        lane deadline, then dispatch everything due. Returns requests
        served this turn."""
        with perf.stage("serve"):
            if wait_s > 0:
                deadline = self.scheduler.next_deadline(
                    self.admission.max_depth)
                now = self._clock()
                timeout = wait_s if deadline is None else max(
                    min(deadline - now, wait_s), 0.0)
                if timeout > 0:
                    with perf.stage("queue"):
                        with self._cv:
                            self._cv.wait(timeout)
            with perf.stage("dispatch"):
                batches = self.scheduler.due(self.admission.max_depth,
                                             self._append_cap)
            n = 0
            for batch in batches:
                self._dispatch(batch)
                n += len(batch.tickets)
        return n

    def run_until_idle(self, timeout_s: float = 120.0) -> int:
        """Serve the current queue to completion on the calling thread
        (deterministic test/synchronous mode). Lanes below their fill
        target dispatch immediately once nothing else is due — idleness
        beats occupancy when the queue has drained."""
        t0 = self._clock()
        total = 0
        while self.scheduler.depth() > 0:
            served = self.step(0.0)
            if served == 0:
                # nothing full: wait out the earliest lane deadline (the
                # same bounded wait the resident worker uses), then the
                # next turn dispatches it
                served = self.step(
                    wait_s=min(self.scheduler.base_wait_s, 0.05))
            total += served
            if self._clock() - t0 > timeout_s:
                raise TimeoutError("run_until_idle exceeded its budget "
                                   f"with {self.scheduler.depth()} queued")
        return total

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._stopping and self.scheduler.depth() == 0:
                    return
            self.step(wait_s=0.05)

    def start(self) -> None:
        """Spawn the resident worker thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stopping = False
        self._thread = threading.Thread(target=self._run,
                                        name="pint-tpu-serve", daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 60.0) -> None:
        """Drain the queue and join the worker."""
        if self._thread is None:
            return
        self._stopping = True
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout_s)
        if self._thread.is_alive():  # pragma: no cover — debug aid
            raise TimeoutError("serving worker did not stop")
        self._thread = None

    # -- telemetry -------------------------------------------------------------------

    def stats(self) -> dict:
        """JSON-ready serving telemetry: throughput counters, bounded
        per-request latency/queue-wait quantiles, pool + shed traffic."""
        out = {
            "served": self.served,
            "dispatches": self.dispatches,
            "shed": self.admission.shed_count,
            "queued": self.scheduler.depth(),
            "waste_ewma": round(self.scheduler.waste_ewma, 4),
            "latency": self.latency.summary("ms"),
            "refit_latency": self.refit_latency.summary("ms"),
            "queue_wait": self.queue_wait.summary("ms"),
            "pool": self.pool.stats(),
        }
        if self.served and self.dispatches:
            out["coalesce_ratio"] = round(self.served / self.dispatches, 3)
        return out
