"""The serving throughput engine: an always-on worker over TimingService
primitives with continuous batching, a warm pool, and admission control.

PR 10 built the physics of serving — O(k) appends, rank-k refits,
fleet-batched full fits — behind a synchronous ``drain()``. This module
is the part that makes it a *service*: a worker loop that keeps the
device saturated with batched likelihood work (the Vela.jl lesson,
arXiv:2412.15858) while bounding what any single client experiences.

The life of a request::

    client thread                      worker thread
    -------------                      -------------
    submit() ──admit──▶ lane  ──due──▶ coalesce ─▶ dispatch ─▶ solve ─▶ finalize
       │        │                                   (pool.get,   (rank-k /    │
       │     ShedError                               restore)    fit_batch)   │
       ▼                                                                      ▼
    ticket.wait() ◀──────────────────────────── result + per-request stamps ──┘

- **submit** admits (bounded queue, per-tenant token buckets,
  ``serve.shed`` on overload — scheduler.py) and queues the request into
  its lane: per-session for appends, per-(fit-kind, row-bucket) skeleton
  class for refits. Returns a :class:`ServeTicket` immediately.
- **the worker** dispatches a lane the moment it fills or its oldest
  request hits the live deadline (base ``PINT_TPU_SERVE_MAX_WAIT_MS``,
  stretched when recent dispatches wasted padding, collapsed under
  queue pressure). Same-session appends coalesce into ONE rank-k
  update; refit lanes run through the fleet engine as one batched
  program (session.py ``batch_refit``). Sessions come from the warm
  :class:`~pint_tpu.serve.pool.SessionPool` (LRU + checkpoint/restore).
- **telemetry**: every stage records into the ``serve`` perf tree
  (``ops/perf.py serve_breakdown``, ≥90% attribution contract) and
  every request feeds bounded :class:`~pint_tpu.ops.perf.QuantileSketch`
  latency/queue-wait distributions — the p50/p99 a replayed-trace bench
  (``python bench.py --smoke --serve``) reports as
  ``serve_p50_ms``/``serve_p99_ms``.

Run modes: :meth:`ServingEngine.start` spawns the resident worker
thread (the always-on shape — `stop()` drains it); for deterministic
tests and synchronous callers, :meth:`run_until_idle` serves the
current queue to completion on the calling thread with identical code
paths.

**Durability + lifecycle hardening (ISSUE 14).** With ``durable_dir``
set, the engine is crash-safe end to end: every admitted request is
appended to the write-ahead journal (serve/journal.py) *before* its
ticket acks admission, ``stop(drain=True)`` flushes the queue +
checkpoints the fleet + closes the journal cleanly, and a fresh process
rebuilds the whole engine from the checkpoints + journal suffix
(serve/recover.py, ``pint_tpu recover``). Request lifecycle:

- **deadlines** — ``submit(deadline_s=...)`` (default
  ``PINT_TPU_SERVE_DEADLINE_MS``) stamps an absolute deadline; a request
  still queued past it is shed with ``serve.deadline`` on the
  degradation ledger instead of occupying a dispatch slot;
- **bounded retry** — a transiently failed dispatch (a NaN-poisoned
  fused fit, a ``fit.host_fallback`` storm) retries up to
  ``PINT_TPU_SERVE_RETRIES`` times with exponential backoff
  (``serve.retry`` on the ledger per attempt), then delivers the error;
- **watchdog + quarantine** — a crash-looping lane
  (``PINT_TPU_SERVE_QUARANTINE_FAILS`` consecutive failed dispatches) or
  a hung dispatch (``PINT_TPU_SERVE_WATCHDOG_S``, detected by the
  watchdog thread, which abandons the hung worker and spawns a
  replacement) quarantines the offending session — ``serve.quarantine``
  on the ledger, refusable under ``PINT_TPU_DEGRADED=error``, new
  submits for it raise :class:`QuarantinedError` — while the rest of
  the fleet keeps serving.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

from pint_tpu.obs import flight, metrics, trace
from pint_tpu.ops import degrade, perf
from pint_tpu.serve.journal import RequestJournal, encode_rows
from pint_tpu.serve.pool import SessionPool
from pint_tpu.serve.scheduler import (AdmissionController,
                                      ContinuousBatchScheduler,
                                      DeadlineError, Lane, QuarantinedError,
                                      ShedError)
from pint_tpu.serve.session import (SessionResult, batch_refit,
                                    coalesce_append_payloads)
from pint_tpu.testing import faults
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.serve")

__all__ = ["ServeTicket", "ServingEngine"]


@dataclass
class ServeTicket:
    """One admitted request's handle: completion event, result slot and
    the per-request SLO stamps (submit → dispatch → done)."""

    session: str
    kind: str                      # "append" | "refit"
    tenant: str
    rows: int                      # payload rows (appends; 1 for refits)
    lane_key: tuple
    payload: dict | None = None
    #: idempotency key: journaled with the request and recorded on the
    #: session once applied, so crash recovery never double-applies
    idem: str = ""
    #: the request's trace id (pint_tpu/obs/trace.py): minted at submit
    #: when PINT_TPU_TRACE is on, journaled with the request, attached
    #: by the worker around the dispatch that serves it ("" = tracing
    #: off — zero-cost)
    trace_id: str = ""
    #: absolute clock time past which the queued request is shed with
    #: ``serve.deadline`` instead of dispatched (None: no deadline)
    deadline: float | None = None
    t_submit: float = 0.0
    #: when submit finished admitting+journaling (the ack): the span
    #: boundary between the "admit" and "queue" trace spans
    t_acked: float = 0.0
    t_dispatch: float | None = None
    t_done: float | None = None
    result: SessionResult | None = None
    error: BaseException | None = None
    _event: threading.Event = field(default_factory=threading.Event)

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> SessionResult:
        """Block until served; raises the shed/solve error when the
        request failed, returns its :class:`SessionResult` otherwise."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request for session {self.session!r} not served within "
                f"{timeout} s")
        if self.error is not None:
            raise self.error
        return self.result

    @property
    def latency_ms(self) -> float | None:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3

    @property
    def queue_ms(self) -> float | None:
        if self.t_dispatch is None:
            return None
        return (self.t_dispatch - self.t_submit) * 1e3


class ServingEngine:
    """Continuous-batching serving engine over a warm session pool (see
    module docstring). Constructor knobs default from the registry
    (``PINT_TPU_SERVE_*``); explicit arguments override for tests."""

    def __init__(self, pool: SessionPool | None = None, *,
                 max_wait_ms: float | None = None,
                 queue_depth: int | None = None,
                 tenant_rps: float | None = None,
                 shed_policy: str | None = None,
                 coalesce_rows: int = 16, refit_batch: int = 4,
                 maxiter: int = 30, clock=time.monotonic,
                 durable_dir: str | Path | None = None,
                 journal: RequestJournal | None = None,
                 deadline_ms: float | None = None,
                 retries: int | None = None,
                 retry_backoff_ms: float | None = None,
                 quarantine_fails: int | None = None,
                 watchdog_s: float | None = None,
                 metrics_port: int | None = None,
                 sleep=time.sleep):
        self.pool = pool if pool is not None else SessionPool()
        self.admission = AdmissionController(
            max_depth=queue_depth, tenant_rps=tenant_rps,
            policy=shed_policy, clock=clock)
        self.scheduler = ContinuousBatchScheduler(
            max_wait_ms=max_wait_ms, coalesce_rows=coalesce_rows,
            refit_batch=refit_batch, clock=clock)
        self.maxiter = maxiter
        self._clock = clock
        self._sleep = sleep
        self._cv = threading.Condition()
        self._stopping = False
        self._draining = False
        self._thread: threading.Thread | None = None
        # durability: WAL every admitted request, checkpoint on drain
        self.durable_dir = Path(durable_dir) if durable_dir else None
        self.journal = journal
        if self.journal is None and self.durable_dir is not None:
            self.journal = RequestJournal(self.durable_dir / "journal")
        # request lifecycle knobs (constructor overrides for tests)
        self.deadline_s = (float(knobs.get("PINT_TPU_SERVE_DEADLINE_MS"))
                           if deadline_ms is None
                           else float(deadline_ms)) * 1e-3
        self.retries = (int(knobs.get("PINT_TPU_SERVE_RETRIES"))
                        if retries is None else int(retries))
        self.retry_backoff_s = (
            float(knobs.get("PINT_TPU_SERVE_RETRY_BACKOFF_MS"))
            if retry_backoff_ms is None else float(retry_backoff_ms)) * 1e-3
        self.quarantine_fails = (
            int(knobs.get("PINT_TPU_SERVE_QUARANTINE_FAILS"))
            if quarantine_fails is None else int(quarantine_fails))
        self.watchdog_s = (float(knobs.get("PINT_TPU_SERVE_WATCHDOG_S"))
                           if watchdog_s is None else float(watchdog_s))
        #: sessions pulled out of service by the watchdog / crash-loop
        #: detector; submits for them raise QuarantinedError
        self.quarantined: set[str] = set()
        self._fail_counts: dict[str, int] = {}
        #: the dispatch currently on the device: (desc, t_start, gen) —
        #: the watchdog's hung-lane signal
        self._inflight: tuple | None = None
        self._worker_gen = 0
        self._watchdog: threading.Thread | None = None
        self._watchdog_stop = threading.Event()
        self._unhang = threading.Event()
        # served-request SLO sketches: bounded memory at any uptime;
        # appends and refits keep separate latency distributions (a
        # full-refit wall would otherwise smear the append p99 the SLO
        # actually bounds)
        self.latency = perf.QuantileSketch()
        self.refit_latency = perf.QuantileSketch()
        self.queue_wait = perf.QuantileSketch()
        # submit-path overhead (ticket mint + journal append + queue
        # offer, in µs): the client-thread tax every request pays before
        # its ack, the thing the two-phase journal append shrinks
        self.submit_lat = perf.QuantileSketch()
        self.served = 0
        self.dispatches = 0
        self.expired = 0
        self.retried = 0
        self.worker_replacements = 0
        # observability (pint_tpu/obs/): crash reports land beside the
        # journal store; the metrics endpoint serves /metrics + /healthz
        # when a port is configured (knob PINT_TPU_METRICS_PORT, or an
        # explicit metrics_port= — 0 means "pick an ephemeral port" when
        # explicit, "off" when it comes from the knob's default)
        self.crash_dir = (self.durable_dir / "crash"
                          if self.durable_dir is not None else None)
        self._metrics_explicit = metrics_port is not None
        self.metrics_port = (int(knobs.get("PINT_TPU_METRICS_PORT"))
                             if metrics_port is None else int(metrics_port))
        self.metrics_server: metrics.MetricsServer | None = None
        self._register_metrics()

    # -- observability ---------------------------------------------------------------

    def _register_metrics(self) -> None:
        """Expose live engine state through the process metrics registry
        (pint_tpu/obs/metrics.py): gauges read THIS engine at scrape
        time (re-registration replaces the callback — the newest engine
        wins), and the latency/queue-wait sketches export as summaries.
        Counters (serve_requests, serve_shed, ...) flow in through the
        perf.add feed — nothing is measured twice."""
        reg = metrics.registry()
        reg.gauge("serve_queue_depth",
                  "requests currently queued in serving lanes",
                  fn=self.scheduler.depth)
        reg.gauge("serve_pool_live", "live sessions in the warm pool",
                  fn=lambda: self.pool.stats()["live"])
        reg.gauge("serve_pool_checkpointed",
                  "sessions evicted to checkpoints",
                  fn=lambda: self.pool.stats()["checkpointed"])
        reg.gauge("serve_quarantined", "sessions pulled out of service",
                  fn=lambda: len(self.quarantined))
        reg.gauge("serve_inflight", "1 while a dispatch is on the device",
                  fn=lambda: 1 if self._inflight is not None else 0)
        reg.gauge("serve_waste_ewma",
                  "padding-waste EWMA steering the lane deadline",
                  fn=lambda: self.scheduler.waste_ewma)
        reg.summary("serve_latency_ms",
                    "end-to-end append latency (submit to done)",
                    sketch=self.latency)
        reg.summary("serve_refit_latency_ms",
                    "end-to-end refit latency (submit to done)",
                    sketch=self.refit_latency)
        reg.summary("serve_queue_wait_ms",
                    "queue wait before the (possibly shared) solve",
                    sketch=self.queue_wait)
        reg.summary("serve_submit_us",
                    "submit-path overhead (mint + journal + offer) in us",
                    sketch=self.submit_lat)

    def health(self) -> tuple[bool, dict]:
        """Readiness for ``/healthz``: ok iff the engine is not draining,
        the journal (when configured) is open, and the worker (when
        started) is alive. The detail block carries the journal/pool/
        watchdog state an operator triages from."""
        worker_alive = self._thread is not None and self._thread.is_alive()
        journal_ok = (self.journal is None
                      or not self.journal._fh.closed)
        ok = (not self._draining and journal_ok
              and (self._thread is None or worker_alive))
        detail = {
            "draining": self._draining,
            "worker_alive": worker_alive,
            "watchdog_alive": (self._watchdog is not None
                               and self._watchdog.is_alive()),
            "queued": self.scheduler.depth(),
            "served": self.served,
            "quarantined": sorted(self.quarantined),
            "pool": self.pool.stats(),
            "journal": (None if self.journal is None
                        else self.journal.stats()),
        }
        return ok, detail

    def _dump_crash(self, reason: str):
        """Write a flight-recorder crash report beside the journal store
        (no-op without a durable_dir). Best-effort: the report must
        never turn a failing dispatch into a worse failure."""
        if self.crash_dir is None:
            return None
        return flight.dump_crash_report(
            self.crash_dir, reason, extra={"engine": self.stats()})

    def _emit_request_trace(self, t: ServeTicket, shared: int) -> None:
        """Reconstruct one served request's named spans from its SLO
        stamps: ``request`` (the root, submit->done) with ``admit`` /
        ``queue`` / ``solve`` children partitioning it — the >=90%
        per-request attribution contract holds by construction, and any
        live spans the dispatch recorded (session append, compiles,
        .aotx loads) join the same trace id."""
        root = f"{t.trace_id}:r"
        kw = dict(trace=t.trace_id, parent=root)
        acked = t.t_acked or t.t_submit
        td = t.t_dispatch if t.t_dispatch is not None else acked
        done = t.t_done
        trace.emit("request", t.t_submit, done - t.t_submit,
                   trace=t.trace_id, span_id=root, session=t.session,
                   kind=t.kind, rows=t.rows, tenant=t.tenant,
                   coalesced=shared)
        trace.emit("admit", t.t_submit, acked - t.t_submit, **kw)
        trace.emit("queue", acked, td - acked, **kw)
        trace.emit("solve", td, done - td, shared=shared, **kw)

    # -- sessions --------------------------------------------------------------------

    def add_session(self, sid: str, session) -> None:
        """Register a fitted resident session under ``sid``."""
        self.pool.put(sid, session)

    def _lane_key(self, sid: str, kind: str) -> tuple:
        if kind == "append":
            return ("append", sid)
        # refits batch across sessions sharing a fleet skeleton class:
        # group by fit kind + padded row bucket so one lane fills one
        # fixed-shape batched program (fitting/batch.py buckets further
        # by exact skeleton — a mixed lane still dispatches correctly,
        # it just fans into more than one bucket)
        from pint_tpu.fitting.incremental import (MIN_APPEND_BUCKET,
                                                  _pow2_at_least)

        ses = self.pool.get(sid)
        bucket = _pow2_at_least(len(ses.toas), MIN_APPEND_BUCKET)
        return ("refit", ses.fitter._fused_kind, bucket)

    def _append_cap(self, sid: str) -> int:
        """Max rows one coalesced dispatch may append and stay inside
        the incremental staleness envelope (PINT_TPU_INCR_MAX_FRAC)."""
        try:
            n = len(self.pool.get(sid).toas)
        except KeyError:
            return self.scheduler.coalesce_rows
        frac = float(knobs.get("PINT_TPU_INCR_MAX_FRAC"))
        return max(1, int(frac * n))

    # -- intake ----------------------------------------------------------------------

    def submit(self, *, session: str, kind: str = "append",
               tenant: str = "default", utc=None, error_us=None,
               freq_mhz=None, obs=None, flags=None,
               deadline_s: float | None = None,
               idem: str | None = None) -> ServeTicket:
        """Admit one request and queue it for the worker; returns its
        :class:`ServeTicket`. Sheds raise :class:`ShedError` (or
        ``DegradedError`` under ``PINT_TPU_DEGRADED=error``) here, at
        the client — overload is an explicit refusal, not a timeout.

        ``deadline_s`` (relative; default ``PINT_TPU_SERVE_DEADLINE_MS``,
        0 disables) bounds how long the request may wait queued before it
        is shed with ``serve.deadline``. ``idem`` is the idempotency key
        journaled with the request (auto-generated when omitted) — a
        client retrying an acked-but-unanswered submit after a crash
        passes the same key and recovery applies it exactly once.

        With a journal configured the record is durably appended BEFORE
        this method returns: an acked request survives a process kill
        (``pint_tpu recover`` replays it); a failed journal write raises
        :class:`~pint_tpu.serve.journal.JournalError` and the request
        was never admitted."""
        if kind not in ("append", "refit"):
            raise ValueError(f"unknown request kind {kind!r}")
        if session not in self.pool:
            raise KeyError(f"unknown session {session!r}")
        if session in self.quarantined:
            raise QuarantinedError(
                f"session {session!r} is quarantined (serve.quarantine on "
                "the degradation ledger); restart or re-add it to resume")
        payload = None
        rows = 1
        if kind == "append":
            payload = dict(utc=utc, error_us=error_us, freq_mhz=freq_mhz,
                           obs=obs, flags=flags)
            rows = len(np.asarray(error_us))
        # mint the request's trace id ("" when tracing is off — every
        # hook below degrades to a no-op); attaching here means any
        # degradation the admit path records (a shed, a rate refusal)
        # carries this trace id on the ledger
        tid = trace.new_trace_id() if trace.enabled() else ""
        with trace.attach(tid or None), perf.stage("serve"):
            with perf.stage("admit"):
                if self._draining:
                    # refuse-while-draining is a shed like any other:
                    # ledger first, explicit error to the client
                    self.admission.refuse(
                        tenant, "drain",
                        f"request for session {session!r} refused: the "
                        "engine is draining for shutdown")
                action = self.admission.admit(tenant,
                                              self.scheduler.depth())
                if action == "drop_oldest":
                    victim = self.scheduler.drop_oldest()
                    if victim is not None:
                        self.admission.record_drop(
                            victim.tenant,
                            f"request from tenant {victim.tenant!r} for "
                            f"session {victim.session!r} dropped to admit "
                            "newer work (PINT_TPU_SERVE_SHED_POLICY="
                            "drop_oldest)")
                        victim.error = ShedError(
                            "dropped by a newer request under "
                            "drop_oldest shed policy")
                        victim.t_done = self._clock()
                        victim._event.set()
                now = self._clock()
                dl = deadline_s if deadline_s is not None else (
                    self.deadline_s if self.deadline_s > 0 else None)
                ticket = ServeTicket(
                    session=session, kind=kind, tenant=tenant, rows=rows,
                    lane_key=self._lane_key(session, kind),
                    payload=payload, t_submit=now,
                    idem=idem or uuid.uuid4().hex, trace_id=tid,
                    deadline=None if dl is None else now + float(dl))
                perf.add("serve_requests")
            if self.journal is not None:
                # the WAL contract: the record is durable (flushed to
                # the OS, fsync-batched) BEFORE the ticket acks; a
                # JournalError propagates and nothing was queued. The
                # trace id rides the record, so a replayed request is
                # joinable against the dead process's trace buffer.
                self.journal.append({
                    "session": session, "kind": kind, "tenant": tenant,
                    "idem": ticket.idem, "deadline_s": dl,
                    "trace": tid,
                    "rows": encode_rows(payload) if kind == "append"
                    else None})
            with perf.stage("admit"):
                self.scheduler.offer(ticket, rows=rows)
            ticket.t_acked = self._clock()
            self.submit_lat.add((ticket.t_acked - ticket.t_submit) * 1e6)
            if perf.active():
                perf.put("serve_submit_us_p50",
                         self.submit_lat.quantile(0.5) * 1.0)
                perf.put("serve_submit_us_p99",
                         self.submit_lat.quantile(0.99) * 1.0)
        with self._cv:
            self._cv.notify()
        return ticket

    # -- the worker ------------------------------------------------------------------

    def _dispatch_append(self, batch: Lane) -> None:
        # the per-session mutex pins the session for the whole mutation:
        # a concurrent LRU eviction try-acquires it and picks another
        # victim instead of capturing a checkpoint mid-append
        with self.pool.session_lock(batch.sid):
            with perf.stage("dispatch"):
                session = self.pool.get(batch.sid)
            with perf.stage("coalesce"):
                merged = coalesce_append_payloads(
                    [t.payload for t in batch.tickets])
                if len(batch.tickets) > 1:
                    perf.add("serve_coalesced", len(batch.tickets))
            with perf.stage("solve"):
                shared = session.append(**merged)
            # applied: record the idempotency keys so a checkpoint taken
            # now captures them and crash recovery dedups instead of
            # re-applying
            for t in batch.tickets:
                if t.idem:
                    session.applied_idem.add(t.idem)
        self._finalize(batch, shared,
                       waste=1.0 - batch.rows / self._append_bucket(
                           batch.rows))
        perf.add("serve_appends", len(batch.tickets))

    @staticmethod
    def _append_bucket(rows: int) -> int:
        from pint_tpu.fitting.incremental import append_bucket

        return append_bucket(rows)

    def _dispatch_refit(self, batch: Lane) -> None:
        # one ticket per (session, request); a session refits ONCE per
        # dispatch — duplicate refit requests share the solve
        sids: list[str] = []
        for t in batch.tickets:
            if t.session not in sids:
                sids.append(t.session)
        # pin every session for the batched mutation (sorted acquire so
        # two refit lanes can never deadlock on overlapping session sets)
        with contextlib.ExitStack() as stack:
            for sid in sorted(sids):
                stack.enter_context(self.pool.session_lock(sid))
            with perf.stage("dispatch"):
                sessions = [self.pool.get(sid) for sid in sids]
            with perf.stage("solve"), perf.collect() as rep:
                results = batch_refit(sessions, maxiter=self.maxiter)
            by_sid = dict(zip(sids, results))
            by_ses = dict(zip(sids, sessions))
            for t in batch.tickets:
                if t.idem:
                    by_ses[t.session].applied_idem.add(t.idem)
        self._finalize(batch, None, by_sid=by_sid,
                       waste=rep.values.get("padding_waste_frac"))
        perf.add("serve_refits", len(batch.tickets))

    def _finalize(self, batch: Lane, shared: SessionResult | None,
                  by_sid: dict | None = None,
                  waste: float | None = None) -> None:
        with perf.stage("finalize"):
            now = self._clock()
            for t in batch.tickets:
                base = shared if shared is not None else by_sid[t.session]
                t.t_dispatch = t.t_dispatch or batch.t_oldest
                t.t_done = now
                t.result = SessionResult(
                    base.result, base.path, t.rows if t.kind == "append"
                    else 0,
                    latency_ms=(now - t.t_submit) * 1e3,
                    reason=base.reason, breakdown=base.breakdown,
                    queue_ms=max(t.t_dispatch - t.t_submit, 0.0) * 1e3)
                (self.latency if t.kind == "append"
                 else self.refit_latency).add(t.result.latency_ms)
                self.queue_wait.add(t.result.queue_ms)
                self.served += 1
                if t.trace_id:
                    self._emit_request_trace(t, shared=len(batch.tickets))
                t._event.set()
            self.dispatches += 1
            perf.add("serve_dispatches")
            self.scheduler.observe_waste(waste)

    def _deliver_error(self, batch: Lane, e: BaseException) -> None:
        now = self._clock()
        for t in batch.tickets:
            if not t._event.is_set():
                t.error = e
                t.t_done = now
                if t.trace_id:
                    # failed requests still close their trace: the root
                    # span carries the error so the buffer answers
                    # "what happened to request X" for failures too
                    trace.emit("request", t.t_submit, now - t.t_submit,
                               trace=t.trace_id, span_id=f"{t.trace_id}:r",
                               session=t.session, kind=t.kind,
                               error=type(e).__name__)
                t._event.set()

    def _batch_sids(self, batch: Lane) -> list[str]:
        sids: list[str] = []
        for t in batch.tickets:
            if t.session not in sids:
                sids.append(t.session)
        return sids

    def _quarantine(self, sid: str, why: str) -> BaseException | None:
        """Pull ``sid`` out of service and put ``serve.quarantine`` on
        the ledger. Returns the ``DegradedError`` under
        ``PINT_TPU_DEGRADED=error`` (the caller delivers the refusal to
        the waiting tickets — raising here would kill the worker the
        rest of the fleet depends on)."""
        self.quarantined.add(sid)
        perf.add("serve_quarantines")
        log.error(f"session {sid!r} quarantined: {why}")
        refused = None
        try:
            degrade.record(
                "serve.quarantine", f"session:{sid}",
                f"session {sid!r} quarantined ({why}); the rest of the "
                "fleet keeps serving, new requests for it are refused",
                bound_us=0.0,  # no wrong answers served; the lane is down
                fix="investigate the failing lane; re-add the session "
                    "(add_session) or recover it from its checkpoint to "
                    "resume, tune PINT_TPU_SERVE_QUARANTINE_FAILS / "
                    "PINT_TPU_SERVE_WATCHDOG_S")
        except degrade.DegradedError as e:
            refused = e
        # quarantine is a crash-report trigger: the flight ring + the
        # active spans (the hung dispatch is still open) + a metrics
        # snapshot land beside the journal for the post-mortem
        self._dump_crash(f"session {sid!r} quarantined: {why}")
        return refused

    def _note_failure(self, batch: Lane, e: BaseException) -> bool:
        """Account one exhausted (post-retry) dispatch failure; a lane
        failing ``quarantine_fails`` times in a row is crash-looping and
        its session(s) are quarantined. Returns True when a quarantine
        fired (which already dumped a crash report)."""
        quarantined = False
        for sid in self._batch_sids(batch):
            n = self._fail_counts.get(sid, 0) + 1
            self._fail_counts[sid] = n
            if n >= self.quarantine_fails and sid not in self.quarantined:
                quarantined = True
                refused = self._quarantine(
                    sid, f"{n} consecutive failed dispatches "
                         f"(last: {type(e).__name__}: {e})")
                if refused is not None:
                    self._deliver_error(batch, refused)
        return quarantined

    def _dispatch(self, batch: Lane) -> None:
        t_d = self._clock()
        for t in batch.tickets:
            t.t_dispatch = t_d
        # trace propagation across the submit->worker thread hop: the
        # batch's primary trace id is attached for the whole dispatch,
        # so every span underneath (session append, TimedProgram
        # compile/.aotx load) and every degradation the solve records is
        # attributed to the request that triggered it
        primary = next((t.trace_id for t in batch.tickets if t.trace_id),
                       None)
        with trace.attach(primary), \
                trace.span("dispatch", lane=str(batch.key),
                           tickets=len(batch.tickets), kind=batch.kind):
            self._dispatch_inner(batch)

    def _dispatch_inner(self, batch: Lane) -> None:
        if faults.trip("serve.crash", f"lane:{batch.key}") is not None:
            # the kill-mid-trace drill: the process dies with the batch
            # admitted + journaled but NOT applied — recovery must replay
            # it (tests/test_recover.py). os._exit skips every finally:
            # exactly what a SIGKILL/OOM looks like to the journal. The
            # flight recorder dumps its ring first — a real OOM-killer
            # gives no such grace, but every crash the process itself
            # can see leaves a post-mortem beside the journal.
            log.error("serve.crash fault: exiting mid-dispatch")
            self._dump_crash("serve.crash fault: killed mid-dispatch "
                             f"(lane {batch.key})")
            os._exit(70)
        flight.note("serve.dispatch", lane=str(batch.key),
                    batch_kind=batch.kind, tickets=len(batch.tickets),
                    trace=trace.current_trace_id())
        attempts = 1 + max(self.retries, 0)
        for attempt in range(attempts):
            self._inflight = (batch, self._clock(), self._worker_gen)
            try:
                mode = faults.trip("serve.dispatch", f"lane:{batch.key}")
                if mode == "fail":
                    raise RuntimeError(
                        "injected dispatch failure (serve.dispatch:fail)")
                if mode == "hang":
                    # a hung device/lane: block until the watchdog has
                    # moved on without this worker (or a 5 s safety
                    # valve, so a watchdog-less engine cannot deadlock)
                    gen0 = self._worker_gen
                    self._unhang.wait(5.0)
                    if self._worker_gen != gen0:
                        # the watchdog retired THIS worker mid-hang: its
                        # tickets were already failed and the session
                        # quarantined — applying the batch now would
                        # land rows the client was told were NOT served
                        return
                if batch.kind == "append":
                    self._dispatch_append(batch)
                else:
                    self._dispatch_refit(batch)
                for sid in self._batch_sids(batch):
                    self._fail_counts.pop(sid, None)
                return
            except Exception as e:  # noqa: BLE001 — retried (bounded, ledger-visible) then DELIVERED to every waiting ticket; nothing is swallowed  # jaxlint: disable=silent-except
                if attempt + 1 < attempts:
                    self.retried += 1
                    perf.add("serve_retries")
                    try:
                        degrade.record(
                            "serve.retry", f"lane:{batch.key}",
                            f"dispatch attempt {attempt + 1} failed "
                            f"({type(e).__name__}: {e}); retrying with "
                            "backoff",
                            bound_us=0.0,  # latency lost, no wrong answer
                            fix="transient by definition — investigate if "
                                "PINT_TPU_SERVE_RETRIES stops absorbing it")
                    except degrade.DegradedError as refusal:
                        # =error refuses the retry: the client gets the
                        # refusal, the lane failure still counts
                        self._deliver_error(batch, refusal)
                        self._note_failure(batch, e)
                        return
                    self._sleep(self.retry_backoff_s * (2 ** attempt))
                    continue
                self._deliver_error(batch, e)
                if not self._note_failure(batch, e):
                    # an unhandled (post-retry) dispatch failure is a
                    # crash-report trigger: the ring + active spans +
                    # metrics explain what led up to it (a quarantine
                    # above already dumped one for this failure)
                    self._dump_crash(
                        f"dispatch failed after {attempts} attempt(s) on "
                        f"lane {batch.key}: {type(e).__name__}: {e}")
                return
            except BaseException as e:  # noqa: BLE001 — delivered then re-raised to the caller  # jaxlint: disable=silent-except
                self._deliver_error(batch, e)
                raise
            finally:
                self._inflight = None

    def _expire_queued(self) -> None:
        """Shed every queued request whose deadline has passed —
        ``serve.deadline`` on the ledger, :class:`DeadlineError` (or the
        ``=error`` refusal) through the ticket — so expired work never
        occupies a dispatch slot. The ``serve.deadline:expire`` fault
        site forces the oldest queued request expired, driving the path
        end-to-end without a clock."""
        now = self._clock()
        expired = self.scheduler.expire(now)
        if (self.scheduler.depth() > 0
                and faults.trip("serve.deadline") is not None):
            victim = self.scheduler.drop_oldest()
            if victim is not None:
                expired.append(victim)
        for t in expired:
            self.expired += 1
            perf.add("serve_deadline_expired")
            err: BaseException = DeadlineError(
                f"request for session {t.session!r} expired after "
                f"{(now - t.t_submit) * 1e3:.1f} ms queued (deadline "
                f"{t.deadline}); shed instead of dispatched")
            try:
                # attached so the serve.deadline ledger event carries
                # the expired request's trace id (joinable post-mortem)
                with trace.attach(t.trace_id or None):
                    degrade.record(
                        "serve.deadline", f"session:{t.session}",
                        f"queued request from tenant {t.tenant!r} for "
                        f"session {t.session!r} passed its deadline and "
                        "was shed",
                        bound_us=0.0,  # no stale answer served
                        fix="raise the submit deadline_s / "
                            "PINT_TPU_SERVE_DEADLINE_MS or add capacity")
            except degrade.DegradedError as refusal:
                err = refusal
            t.error = err
            t.t_done = now
            if t.trace_id:
                trace.emit("request", t.t_submit, now - t.t_submit,
                           trace=t.trace_id, span_id=f"{t.trace_id}:r",
                           session=t.session, kind=t.kind,
                           error=type(err).__name__)
            t._event.set()

    def step(self, wait_s: float = 0.0) -> int:
        """One worker turn: (optionally) wait for work or the earliest
        lane deadline, shed expired requests, then dispatch everything
        due. Returns requests served this turn."""
        with perf.stage("serve"):
            if wait_s > 0:
                deadline = self.scheduler.next_deadline(
                    self.admission.max_depth)
                now = self._clock()
                timeout = wait_s if deadline is None else max(
                    min(deadline - now, wait_s), 0.0)
                if timeout > 0:
                    with perf.stage("queue"):
                        with self._cv:
                            self._cv.wait(timeout)
            self._expire_queued()
            with perf.stage("dispatch"):
                batches = self.scheduler.due(self.admission.max_depth,
                                             self._append_cap)
            n = 0
            for bi, batch in enumerate(batches):
                gen_before = self._worker_gen
                self._dispatch(batch)
                n += len(batch.tickets)
                if self._worker_gen != gen_before:
                    # the watchdog retired THIS worker mid-turn: hand
                    # the not-yet-dispatched batches back to the
                    # scheduler so the replacement worker serves them —
                    # an abandoned worker must not strand popped work
                    for later in batches[bi + 1:]:
                        for t in later.tickets:
                            if not t._event.is_set():
                                self.scheduler.offer(t, rows=t.rows)
                    break
        return n

    def run_until_idle(self, timeout_s: float = 120.0) -> int:
        """Serve the current queue to completion on the calling thread
        (deterministic test/synchronous mode). Lanes below their fill
        target dispatch immediately once nothing else is due — idleness
        beats occupancy when the queue has drained."""
        t0 = self._clock()
        total = 0
        while self.scheduler.depth() > 0:
            served = self.step(0.0)
            if served == 0:
                # nothing full: wait out the earliest lane deadline (the
                # same bounded wait the resident worker uses), then the
                # next turn dispatches it
                served = self.step(
                    wait_s=min(self.scheduler.base_wait_s, 0.05))
            total += served
            if self._clock() - t0 > timeout_s:
                raise TimeoutError("run_until_idle exceeded its budget "
                                   f"with {self.scheduler.depth()} queued")
        return total

    def _run(self, gen: int) -> None:
        while True:
            with self._cv:
                if self._worker_gen != gen:
                    return             # replaced by the watchdog
                if self._stopping and self.scheduler.depth() == 0:
                    return
            self.step(wait_s=0.05)

    # -- the watchdog ----------------------------------------------------------------

    def _watchdog_check(self) -> bool:
        """One watchdog turn: when the current worker has been inside a
        single dispatch longer than ``watchdog_s``, quarantine the hung
        lane's session(s), fail its waiting tickets, abandon the hung
        worker (its generation is retired — it exits whenever the hang
        releases) and spawn a replacement so the rest of the fleet keeps
        serving. Returns True when it intervened."""
        snap = self._inflight
        if snap is None:
            return False
        batch, t_start, gen = snap
        if gen != self._worker_gen:
            return False               # the hung worker is already retired
        if self._clock() - t_start < self.watchdog_s:
            return False
        refusal = None
        for sid in self._batch_sids(batch):
            refusal = self._quarantine(
                sid, f"dispatch hung for more than {self.watchdog_s:g} s "
                     "(watchdog)") or refusal
        self._deliver_error(batch, refusal if refusal is not None
                            else QuarantinedError(
                                "dispatch hung past the watchdog "
                                "threshold; session quarantined"))
        with self._cv:
            self._worker_gen += 1
            gen2 = self._worker_gen
        self.worker_replacements += 1
        perf.add("serve_worker_replacements")
        self._unhang.set()             # release a fault-injected hang
        log.error("watchdog: abandoned a hung worker and spawned a "
                  "replacement; the fleet keeps serving")
        self._thread = threading.Thread(
            target=self._run, args=(gen2,),
            name=f"pint-tpu-serve-{gen2}", daemon=True)
        self._thread.start()
        return True

    def _watchdog_run(self) -> None:
        tick = max(min(self.watchdog_s / 4.0, 0.25), 0.01)
        while not self._watchdog_stop.wait(tick):
            # the heartbeat is flight-recorder state: a crash report
            # shows whether the watchdog was alive and what it saw
            flight.note("watchdog.beat",
                        inflight=self._inflight is not None,
                        queued=self.scheduler.depth())
            self._watchdog_check()

    def start(self) -> None:
        """Spawn the resident worker thread (idempotent), plus the
        watchdog thread when ``watchdog_s > 0``, the metrics endpoint
        when a port is configured, and the SIGUSR1 crash-report hook
        when the engine is durable."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stopping = False
        self._draining = False
        self._thread = threading.Thread(
            target=self._run, args=(self._worker_gen,),
            name="pint-tpu-serve", daemon=True)
        self._thread.start()
        if self.watchdog_s > 0 and (self._watchdog is None
                                    or not self._watchdog.is_alive()):
            self._watchdog_stop.clear()
            self._watchdog = threading.Thread(
                target=self._watchdog_run, name="pint-tpu-serve-watchdog",
                daemon=True)
            self._watchdog.start()
        # /metrics + /healthz: knob port > 0 serves there; an EXPLICIT
        # metrics_port=0 binds an ephemeral port (tests/bench); the
        # knob's 0 default stays off
        want = self.metrics_port > 0 or (self._metrics_explicit
                                         and self.metrics_port == 0)
        if want and self.metrics_server is None:
            self.metrics_server = metrics.MetricsServer(
                port=self.metrics_port, health_fn=self.health)
            self.metrics_port = self.metrics_server.start()
        if self.crash_dir is not None:
            flight.install_signal_handler(self.crash_dir)

    def checkpoint(self) -> list[str]:
        """Durably checkpoint the whole fleet into ``durable_dir`` and
        compact the journal to the boundary (serve/recover.py)."""
        if self.durable_dir is None:
            raise ValueError("engine has no durable_dir configured")
        from pint_tpu.serve.recover import checkpoint_fleet

        return checkpoint_fleet(self.pool, self.durable_dir,
                                journal=self.journal)

    def stop(self, timeout_s: float = 60.0, drain: bool = True) -> None:
        """Stop serving. ``drain=True`` (the graceful shutdown, also the
        CLI's SIGTERM path): stop admitting (late submits shed with an
        explicit refusal), flush every queued lane, fsync the journal,
        checkpoint all pooled sessions and mark the journal cleanly
        closed — so recovery takes the fast no-replay path and zero
        in-flight requests are lost. ``drain=False`` abandons the queue
        (crash-like; the journal keeps the records for recovery)."""
        self._draining = True
        if self._thread is not None:
            self._stopping = True
            with self._cv:
                if not drain:
                    # abandon the queue: retire the worker generation so
                    # it exits at its next loop check instead of draining
                    self._worker_gen += 1
                self._cv.notify_all()
            self._thread.join(timeout_s)
            if self._thread.is_alive():  # pragma: no cover — debug aid
                raise TimeoutError("serving worker did not stop")
            self._thread = None
        if drain and self.scheduler.depth() > 0:
            # no worker (synchronous mode): flush the queue here
            self.run_until_idle(timeout_s)
        if self._watchdog is not None:
            self._watchdog_stop.set()
            self._watchdog.join(timeout_s)
            self._watchdog = None
        if drain:
            if self.durable_dir is not None:
                self.checkpoint()
            if self.journal is not None:
                self.journal.close(clean=True)
        elif self.journal is not None:
            self.journal.fsync()       # crash-like stop: records survive
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    # -- telemetry -------------------------------------------------------------------

    def stats(self) -> dict:
        """JSON-ready serving telemetry: throughput counters, bounded
        per-request latency/queue-wait quantiles, pool + shed traffic."""
        out = {
            "served": self.served,
            "dispatches": self.dispatches,
            "shed": self.admission.shed_count,
            "expired": self.expired,
            "retried": self.retried,
            "quarantined": sorted(self.quarantined),
            "worker_replacements": self.worker_replacements,
            "queued": self.scheduler.depth(),
            "waste_ewma": round(self.scheduler.waste_ewma, 4),
            "latency": self.latency.summary("ms"),
            "refit_latency": self.refit_latency.summary("ms"),
            "queue_wait": self.queue_wait.summary("ms"),
            "submit": self.submit_lat.summary("us"),
            "pool": self.pool.stats(),
        }
        if self.journal is not None:
            out["journal"] = self.journal.stats()
        if self.served and self.dispatches:
            out["coalesce_ratio"] = round(self.served / self.dispatches, 3)
        if self.metrics_server is not None:
            out["metrics_port"] = self.metrics_port
        return out
