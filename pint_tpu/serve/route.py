"""Consistent session→replica routing: rendezvous (HRW) hashing.

A replicated serving fleet (serve/fleet.py) needs every component that
routes a request — the gateway, the controller, a recovering survivor —
to agree on which replica owns a session WITHOUT a coordination service.
Rendezvous (highest-random-weight) hashing gives exactly that: every
router computes ``score(session, replica) = blake2b(replica || session)``
for the live replica set and picks the max. Properties the fleet leans
on (locked by tests/test_fleet.py):

- **Deterministic + coordination-free** — same inputs, same owner, in
  any process, forever (the hash is keyed content, never id()/seed).
- **Minimal disruption** — adding a replica to a fleet of R steals only
  the sessions whose new score beats every old one: ~1/(R+1) of the
  keyspace moves, everything else stays warm where it is. Removing a
  replica reassigns ONLY its own sessions, spread over the survivors by
  the same scores — which is why a crashed replica's sessions can be
  absorbed by recomputing ``owner(sid, survivors)`` with no handoff
  table (serve/fleet.py ``absorb``).
- **Uniform** — scores are independent uniform hashes, so S sessions
  spread ~S/R per replica without a rebalancing pass.

Explicit placement overrides (a live migration pinning a hot session to
a chosen replica) layer ON TOP of this in the fleet's routing table —
the pure function here never carries state.
"""

from __future__ import annotations

import hashlib

__all__ = ["owner", "rank", "score"]


def score(replica: str, key: str) -> int:
    """The HRW weight of ``replica`` for ``key``: a 64-bit keyed hash,
    stable across processes and Python versions (blake2b is seedless —
    unlike ``hash()``, which PYTHONHASHSEED perturbs per process)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(replica.encode())
    h.update(b"\x00")                  # unambiguous (replica, key) framing
    h.update(key.encode())
    return int.from_bytes(h.digest(), "big")


def rank(key: str, replicas) -> list[str]:
    """Every replica ordered by descending HRW score for ``key`` (ties
    broken by name so the order is total). ``rank(...)[0]`` is the
    owner; ``rank(...)[1]`` is the natural failover target."""
    reps = sorted(set(replicas))
    if not reps:
        raise ValueError("cannot route: empty replica set")
    return sorted(reps, key=lambda r: (-score(r, key), r))


def owner(key: str, replicas) -> str:
    """The replica that owns ``key`` under rendezvous hashing."""
    return rank(key, replicas)[0]
