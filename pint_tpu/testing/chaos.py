"""Composable chaos: seeded multi-fault schedules + invariant monitors.

Single-fault drills (testing/faults.py) prove each degradation path in
isolation; real incidents stack — a replica crash DURING a journal-full
episode WHILE a campaign is resuming. :class:`ChaosSchedule` composes
fault sites into one deterministic timeline:

- a schedule is a list of :class:`ChaosEvent`\\ s — ``(t_offset_s,
  site, mode, count, target)`` — where ``target=None`` arms the local
  process (:func:`faults.arm`) and a URL arms a REMOTE serving process
  through its ``/v1/fault`` endpoint (the same surface the fleet bench
  uses), so one schedule spans engine + fleet + campaign processes;
- :meth:`ChaosSchedule.randomized` draws a schedule from a seeded
  ``np.random.default_rng`` — same seed, same timeline, so a chaos soak
  that fails REPLAYS exactly;
- :meth:`start` fires the timeline from a daemon thread (the bench
  soak); :meth:`arm_now` arms everything immediately (deterministic
  tier-1 drills — no wall-clock in the loop).

After the disturbed run, **invariant monitors** decide green/red —
declarative callables returning ``(ok, detail)``:

- :func:`ledger_explained` — every degradation kind on the ledger is
  explained by a scheduled fault (via the KIND_DRILLS inversion) or an
  explicit allowance: chaos may cause NOTHING the schedule doesn't
  predict;
- :func:`requests_lost_zero` — no acked request vanished across
  crash/recover/absorb;
- :func:`parity_within` — the disturbed run's numbers match the
  undisturbed twin's to tolerance (default 1e-10);
- :func:`traces_on_warm_zero` — chaos never silently invalidated the
  warm compile caches.

``python bench.py --smoke --chaos`` runs the soak leg: a replicated
fleet + client load under a >= 3-kind schedule, all monitors green.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from pint_tpu.ops import degrade
from pint_tpu.testing import faults
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.chaos")

__all__ = ["ChaosEvent", "ChaosSchedule", "check_invariants",
           "ledger_explained", "parity_within", "requests_lost_zero",
           "traces_on_warm_zero"]


@dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault: arm ``site`` with ``mode`` for ``count``
    firings at ``t_offset_s`` after the schedule starts, locally
    (``target=None``) or in the serving process at ``target`` (a base
    URL with a ``/v1/fault`` endpoint)."""

    t_offset_s: float
    site: str
    mode: str
    count: int = 1
    target: str | None = None

    @property
    def spec(self) -> str:
        return f"{self.site}:{self.mode}*{self.count}"


class ChaosSchedule:
    """A deterministic multi-fault timeline (see module docstring)."""

    def __init__(self, events: list[ChaosEvent], seed: int | None = None):
        self.events = sorted(events, key=lambda e: (e.t_offset_s, e.site))
        self.seed = seed
        #: (t_offset_s, spec, target) for every event actually armed
        self.armed_log: list[tuple[float, str, str | None]] = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    @classmethod
    def randomized(cls, seed: int, menu: list[tuple[str, str]],
                   duration_s: float, n_events: int,
                   targets: list[str | None] = (None,)) -> "ChaosSchedule":
        """Draw ``n_events`` events from ``menu`` (site, mode) pairs,
        offsets uniform over ``[0, duration_s)``, targets uniform over
        ``targets`` — all from one seeded generator, so the same seed
        reproduces the same timeline bit-for-bit."""
        rng = np.random.default_rng(seed)
        targets = list(targets)
        events = []
        for _ in range(n_events):
            site, mode = menu[int(rng.integers(len(menu)))]
            events.append(ChaosEvent(
                t_offset_s=round(float(rng.uniform(0.0, duration_s)), 3),
                site=site, mode=mode,
                target=targets[int(rng.integers(len(targets)))]))
        return cls(events, seed=seed)

    def kinds(self) -> set[str]:
        """The distinct fault kinds (site, mode) in the schedule — the
        bench's >= 3-concurrent-kinds floor counts these."""
        return {(e.site, e.mode) for e in self.events}

    def explained_kinds(self) -> set[str]:
        """Degradation kinds this schedule can legitimately put on the
        ledger: the KIND_DRILLS inversion — every registered kind whose
        drill site/mode appears in the schedule. One scheduled fault
        may explain several kinds (``serve.dispatch:fail`` drives both
        ``serve.retry`` and ``serve.quarantine``)."""
        scheduled = self.kinds()
        out = set()
        for kind, drill in faults.KIND_DRILLS.items():
            if drill[0] == "site" and (drill[1], drill[2]) in scheduled:
                out.add(kind)
        return out

    # -- arming -----------------------------------------------------------------

    def _arm(self, e: ChaosEvent) -> None:
        if e.target is None:
            faults.arm(e.site, e.mode, e.count)
        else:
            from pint_tpu.serve.gateway import http_json

            http_json(e.target + "/v1/fault", {"spec": e.spec})
        self.armed_log.append((e.t_offset_s, e.spec, e.target))
        log.info(f"chaos: armed {e.spec} "
                 f"{'locally' if e.target is None else 'at ' + e.target} "
                 f"(t+{e.t_offset_s:.3f}s)")

    def arm_now(self) -> "ChaosSchedule":
        """Arm every event immediately, in timeline order — the
        deterministic form the tier-1 drills use (no wall-clock between
        a test and its faults). Returns self for chaining."""
        for e in self.events:
            self._arm(e)
        return self

    def start(self) -> "ChaosSchedule":
        """Fire the timeline on wall-clock offsets from a daemon thread
        (the bench soak form). :meth:`join` waits for the last event;
        :meth:`stop` cancels the remainder."""
        def _run():
            t0 = time.monotonic()
            for e in self.events:
                delay = e.t_offset_s - (time.monotonic() - t0)
                if delay > 0 and self._stop.wait(delay):
                    return
                if self._stop.is_set():
                    return
                self._arm(e)

        self._stop.clear()
        self._thread = threading.Thread(target=_run, name="chaos-schedule",
                                        daemon=True)
        self._thread.start()
        return self

    def join(self, timeout_s: float = 120.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout_s)

    def stop(self) -> None:
        self._stop.set()
        self.join(10.0)


# -- invariant monitors -------------------------------------------------------------

def ledger_explained(schedule: ChaosSchedule, allowed: tuple = ()):
    """Monitor: every degradation kind on the local ledger is explained
    by a scheduled fault or explicitly ``allowed`` — chaos must cause
    nothing the schedule doesn't predict."""
    def check():
        ok_kinds = schedule.explained_kinds() | set(allowed)
        seen = {e.kind for e in degrade.events()}
        orphans = sorted(seen - ok_kinds)
        return (not orphans,
                f"ledger kinds {sorted(seen)} vs explained "
                f"{sorted(ok_kinds)}; unexplained: {orphans}")
    check.__name__ = "ledger_explained"
    return check


def requests_lost_zero(reports) -> tuple[bool, str]:
    """Monitor payload: ``requests_lost == 0`` in every recovery /
    absorb / READY report (pass a list of dicts carrying the key)."""
    lost = {i: r.get("requests_lost") for i, r in enumerate(reports)
            if r.get("requests_lost")}
    return (not lost, f"requests_lost by report: {lost or 'all zero'}")


def parity_within(disturbed, undisturbed, tol: float = 1e-10
                  ) -> tuple[bool, str]:
    """Monitor payload: the disturbed run's numbers equal the
    undisturbed twin's to ``tol`` (arrays or scalars, nested dicts ok).
    ``tol=0`` demands bitwise equality."""
    def _flat(x, prefix=""):
        if isinstance(x, dict):
            for k in sorted(x):
                yield from _flat(x[k], f"{prefix}{k}.")
        else:
            yield prefix.rstrip("."), np.asarray(x)

    a = dict(_flat(disturbed))
    b = dict(_flat(undisturbed))
    if a.keys() != b.keys():
        return False, (f"key mismatch: {sorted(a.keys() ^ b.keys())}")
    worst = ("", 0.0)
    for k in a:
        if a[k].shape != b[k].shape:
            return False, f"shape mismatch at {k}: {a[k].shape} vs {b[k].shape}"
        if a[k].dtype.kind in "fc":
            d = float(np.max(np.abs(a[k] - b[k]))) if a[k].size else 0.0
        else:
            d = 0.0 if np.array_equal(a[k], b[k]) else float("inf")
        if d > worst[1]:
            worst = (k, d)
    return (worst[1] <= tol,
            f"max |disturbed - twin| = {worst[1]:.3e} at "
            f"{worst[0] or '<all>'} (tol {tol:g})")


def traces_on_warm_zero(ready_reports) -> tuple[bool, str]:
    """Monitor payload: no warm-started process compiled anything —
    chaos never silently invalidated the content-addressed caches."""
    traces = {i: r.get("traces_on_warm") for i, r in enumerate(ready_reports)
              if r.get("traces_on_warm")}
    return (not traces, f"traces_on_warm by report: {traces or 'all zero'}")


def check_invariants(monitors: dict) -> tuple[bool, dict]:
    """Evaluate named monitors — each a zero-arg callable returning
    ``(ok, detail)`` — into ``(all_green, {name: (ok, detail)})``."""
    results = {name: fn() for name, fn in monitors.items()}
    return all(ok for ok, _ in results.values()), results
