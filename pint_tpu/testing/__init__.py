"""Test-support machinery that ships with the package.

:mod:`pint_tpu.testing.faults` — the deterministic fault-injection
harness that drives every graceful-degradation path end-to-end in tier-1
(tests/test_degrade.py): injected network refusals, timeouts, corrupt
payloads, and NaN poisoning of fused fit programs. Shipping it in the
package (rather than under tests/) keeps the injection points — the
``maybe_raise``/``mangle``/``poison_nonfinite`` hooks that production
modules call — importable from anywhere, including the docs walkthrough
and operator smoke checks against a staging deployment.
"""

from pint_tpu.testing import faults  # noqa: F401

__all__ = ["faults"]
