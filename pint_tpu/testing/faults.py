"""Deterministic fault injection for the robustness layer.

Every graceful-degradation path in pint_tpu (the ledger taxonomy,
ops/degrade.py) is driven end-to-end in tier-1 by faults injected here —
no real network, no flaky timing. Production modules call the hooks at
their failure points; the hooks are inert (one dict lookup) unless a
fault is armed, so the instrumented paths cost nothing in production.

Sites and modes
---------------
========================  =====================================================
site                      armed modes
========================  =====================================================
``fetch``                 ``refuse`` (ConnectionRefusedError), ``timeout``
                          (TimeoutError) — raised by :func:`maybe_raise`
                          before each download attempt (utils/fetch.py)
``fetch.payload``         ``truncate`` (empty payload), ``corrupt`` (garbage
                          bytes) — applied by :func:`mangle` to the downloaded
                          bytes before the atomic write
``fit.fused``             ``nan`` — :func:`poison_nonfinite` NaN-fills the
                          fused LM loop's outputs (fitting/sharded.py)
``fit.step``              ``nan`` — same, for the per-step fused programs
                          dispatched through adaptive_fused (ops/compile.py)
``fit.incremental``       ``stale`` — :func:`trip` makes the incremental
                          append refit declare its cached linearization
                          stale, driving the ``fit.incremental_fallback``
                          full-refit path (fitting/incremental.py)
``serve.admit``           ``shed`` — :func:`trip` makes the serving
                          admission controller shed the request as if the
                          queue were at depth, driving the ``serve.shed``
                          overload path (serve/scheduler.py)
``serve.pool``            ``evict`` — :func:`trip` makes the warm session
                          pool evict the requested session before serving
                          it, driving the ``serve.evict`` +
                          checkpoint-restore path (serve/pool.py)
``serve.journal``         ``torn`` (a genuinely torn frame reaches disk,
                          then the write raises — the crash-mid-write
                          shape recovery truncates), ``corrupt`` (the
                          payload is bit-flipped under a valid-looking
                          frame — silent rot the read path quarantines),
                          ``enospc`` (the append sees a disk-full
                          OSError — ``serve.journal_full`` on the
                          ledger, the write shed with JournalError/503
                          while reads continue) — applied by the
                          journal writer (serve/journal.py)
``serve.dispatch``        ``fail`` (one dispatch attempt raises, driving
                          the bounded-retry ``serve.retry`` path and,
                          exhausted, the crash-loop ``serve.quarantine``
                          path), ``hang`` (the dispatch blocks until the
                          watchdog abandons the worker) — serve/engine.py
``serve.deadline``        ``expire`` — :func:`trip` makes the engine shed
                          its oldest queued request as if its deadline
                          had passed, driving the ``serve.deadline``
                          path without a clock (serve/engine.py)
``serve.crash``           ``exit`` — the dispatch path calls
                          ``os._exit`` mid-trace (admitted + journaled,
                          not applied): the kill-mid-trace recovery
                          drill (serve/engine.py, tests/test_recover.py);
                          in a replicated fleet the same site is the
                          kill-one-replica chaos drill — survivors
                          absorb the victim's sessions with
                          ``serve.replica_lost`` on the ledger
                          (serve/fleet.py, bench.py --smoke --fleet)
``serve.migrate``         ``force`` — :func:`trip` makes the fleet
                          controller live-migrate the target session to
                          another replica before forwarding the request,
                          driving the ``serve.migrate``
                          checkpoint-handoff path end-to-end
                          (serve/fleet.py)
``serve.ready``           ``hang`` (the replica worker blocks before its
                          ``READY::`` handshake — the parent's
                          ``PINT_TPU_FLEET_READY_TIMEOUT_S`` budget
                          reaps it), ``exit`` (the worker dies before
                          the handshake) — both drive the degraded
                          R−1 fleet start with ``serve.replica_lost``
                          on the ledger (serve/fleet.py spawn_all)
``campaign.run``          ``kill`` — the campaign loop ``os._exit(70)``s
                          after durably checkpointing a completed unit
                          (the preemption drill: a fresh process must
                          resume bitwise-identically,
                          ``campaign.resumed`` on the ledger —
                          campaign/runner.py)
``campaign.checkpoint``   ``kill`` (the checkpoint writer dies mid-write
                          — a torn ``.tmp`` reaches disk, the previous
                          generation stays intact behind the atomic
                          rename), ``corrupt`` (the payload is
                          bit-flipped under a valid-looking frame — the
                          read path quarantines it,
                          ``campaign.checkpoint_corrupt``) — applied
                          by the shared crc-framed checkpoint writer
                          (serve/recover.py), so the drill covers both
                          fleet ``SessionCheckpoint`` stores and campaign
                          snapshots
========================  =====================================================

Arming
------
Programmatically (tests)::

    from pint_tpu.testing import faults
    faults.arm("fetch", "refuse", times=2)   # next 2 attempts refused
    ...
    faults.reset()

or via the ``PINT_TPU_FAULTS`` knob for whole-process runs (smoke checks
against a staging deployment): a comma-separated ``site:mode[*N]`` spec,
e.g. ``PINT_TPU_FAULTS="fetch:timeout*2,fit.fused:nan"``. ``*N`` bounds
the fault to the first N firings; without it the fault fires every time.
The spec is re-parsed whenever the knob's value changes, so tests can
monkeypatch it mid-process.

Every firing is appended to :data:`fired` (site, mode, context) so tests
can unit-lock attempt counts without real network access.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from pint_tpu.utils import knobs

__all__ = ["KIND_DRILLS", "arm", "arm_spec", "fired", "mangle",
           "maybe_raise", "armed", "poison_nonfinite", "reset", "trip"]

#: the fault-taxonomy completeness contract (tests/test_degrade.py gate):
#: EVERY degradation kind registered in ops/degrade.py KINDS maps here to
#: the injected-fault site that drives it end-to-end — ``("site", name,
#: mode)`` — or to a documented exemption ``("env", why)`` for kinds
#: driven by an engineered environment instead of a fault hook. A new
#: ledger kind without an entry fails tier-1: no kind ships without an
#: injection drill.
KIND_DRILLS: dict[str, tuple] = {
    "clock.zero_corrections": (
        "env", "engineered empty clock environment — no discoverable "
               "clock files (tests/test_degrade.py bare_clock_env)"),
    "clock.stale_cache": ("site", "fetch", "timeout"),
    "clock.beyond_table": (
        "env", "TOAs constructed past the clock table's last entry "
               "(tests/test_degrade.py / test_clock.py)"),
    "eop.outside_table": (
        "env", "epochs outside a configured finals2000A table "
               "(tests/test_eop.py)"),
    "ephemeris.analytic_fallback": (
        "env", "a DE kernel requested with no PINT_TPU_EPHEM configured "
               "(tests/test_degrade.py, docs/ROBUSTNESS.md)"),
    "fit.host_fallback": ("site", "fit.fused", "nan"),
    "fit.incremental_fallback": ("site", "fit.incremental", "stale"),
    "fit.aot_layout_fallback": (
        "env", "an AOT executable handed operands with a mismatched "
               "layout/sharding (tests/test_aot.py "
               "test_layout_fallback_sticky_single_event)"),
    "serve.shed": ("site", "serve.admit", "shed"),
    "serve.evict": ("site", "serve.pool", "evict"),
    "serve.deadline": ("site", "serve.deadline", "expire"),
    "serve.retry": ("site", "serve.dispatch", "fail"),
    "serve.quarantine": ("site", "serve.dispatch", "fail"),
    "serve.journal_truncated": ("site", "serve.journal", "torn"),
    "serve.journal_corrupt": ("site", "serve.journal", "corrupt"),
    "serve.journal_full": ("site", "serve.journal", "enospc"),
    "campaign.resumed": ("site", "campaign.run", "kill"),
    "campaign.checkpoint_corrupt": ("site", "campaign.checkpoint",
                                    "corrupt"),
    "serve.migrate": ("site", "serve.migrate", "force"),
    "serve.replica_lost": ("site", "serve.crash", "exit"),
    "fetch.mirror_failed": ("site", "fetch", "refuse"),
    "fetch.corrupt_quarantined": ("site", "fetch.payload", "corrupt"),
    "obs.zero_velocity": (
        "env", "spacecraft TOAs built without velocity flags "
               "(tests/test_astro.py)"),
}


@dataclass
class _Fault:
    mode: str
    remaining: int | None  # None = unbounded


_lock = threading.Lock()
_armed: dict[str, _Fault] = {}
#: log of every fault firing: (site, mode, context) tuples
fired: list[tuple[str, str, str]] = []

# env-spec cache: (raw knob string, parsed site -> _Fault)
_env_cache: tuple[str | None, dict[str, _Fault]] = (None, {})


def reset() -> None:
    """Disarm everything and clear the firing log (test isolation)."""
    global _env_cache
    with _lock:
        _armed.clear()
        fired.clear()
        _env_cache = (None, {})


def arm(site: str, fault_mode: str, times: int | None = 1) -> None:
    """Arm `site` to fail with `fault_mode` for the next `times` firings
    (None = every firing until :func:`reset`)."""
    with _lock:
        _armed[site] = _Fault(fault_mode, times)


def arm_spec(spec: str) -> list[str]:
    """Arm every fault in a ``site:mode[*N][,...]`` spec string (the
    ``PINT_TPU_FAULTS`` grammar) programmatically — the remote-control
    surface a fleet replica's ``/v1/fault`` endpoint exposes so a chaos
    drill can arm a fault inside a running worker process without
    touching its environment. Returns the armed site names."""
    parsed = _parse_env(spec)
    with _lock:
        _armed.update(parsed)
    return sorted(parsed)


def _parse_env(raw: str) -> dict[str, _Fault]:
    out: dict[str, _Fault] = {}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok or ":" not in tok:
            continue
        site, _, spec = tok.partition(":")
        spec, _, n = spec.partition("*")
        out[site.strip()] = _Fault(spec.strip(), int(n) if n else None)
    return out


def _take(site: str) -> _Fault | None:
    """The armed fault for `site`, consuming one firing; None when inert."""
    global _env_cache
    with _lock:
        f = _armed.get(site)
        if f is None:
            raw = knobs.get("PINT_TPU_FAULTS") or ""
            if raw != _env_cache[0]:
                _env_cache = (raw, _parse_env(raw))
            f = _env_cache[1].get(site)
        if f is None:
            return None
        if f.remaining is not None:
            if f.remaining <= 0:
                return None
            f.remaining -= 1
        return f


def armed(site: str) -> bool:
    """True when `site` has firings left (does not consume one)."""
    with _lock:
        f = _armed.get(site)
        if f is None:
            raw = knobs.get("PINT_TPU_FAULTS") or ""
            parsed = _env_cache[1] if raw == _env_cache[0] else _parse_env(raw)
            f = parsed.get(site)
        return f is not None and (f.remaining is None or f.remaining > 0)


def maybe_raise(site: str, context: str = "") -> None:
    """Raise the armed exception-mode fault for `site`, if any."""
    f = _take(site)
    if f is None:
        return
    fired.append((site, f.mode, context))
    if f.mode == "refuse":
        raise ConnectionRefusedError(
            f"injected connection refusal at {site} ({context})")
    if f.mode == "timeout":
        raise TimeoutError(f"injected timeout at {site} ({context})")
    raise RuntimeError(f"injected fault {f.mode!r} at {site} ({context})")


def trip(site: str, context: str = "") -> str | None:
    """Consume one firing of `site` and return its mode (None when
    inert) — the generic hook for control-flow faults that neither raise
    nor mangle payloads (e.g. the incremental-refit staleness drill)."""
    f = _take(site)
    if f is None:
        return None
    fired.append((site, f.mode, context))
    return f.mode


def mangle(site: str, data: bytes, context: str = "") -> bytes:
    """Apply the armed payload-corruption fault for `site` to `data`."""
    f = _take(site)
    if f is None:
        return data
    fired.append((site, f.mode, context))
    if f.mode == "truncate":
        return b""
    if f.mode == "corrupt":
        return b"\x00CORRUPT\x00" * 3
    return data


def poison_nonfinite(site: str, out, context: str = ""):
    """NaN-fill every floating leaf of `out` when `site` is armed with
    mode ``nan`` — simulates a fused device program underflowing to
    non-finite results so the sticky host-fallback path is exercisable
    on any backend."""
    f = _take(site)
    if f is None:
        return out
    fired.append((site, f.mode, context))
    import jax
    import numpy as np

    def nanify(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return x

    return jax.tree_util.tree_map(nanify, out)
