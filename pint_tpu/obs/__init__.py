"""Observability: the ops surface of the serving stack (ISSUE 15).

Three pillars, one package:

- :mod:`pint_tpu.obs.trace` — per-request tracing: context-propagated
  trace/span ids minted at ``ServingEngine.submit``, riding the ticket
  and the write-ahead journal record, threaded through admit/queue/
  dispatch/solve and down into ``TimedProgram`` compiles; spans export
  as JSON Lines to a bounded buffer with a per-request >=90%
  attribution contract. Zero-cost when ``PINT_TPU_TRACE`` is off.
- :mod:`pint_tpu.obs.metrics` — a process-global registry of counters/
  gauges/histograms FED by the existing telemetry surfaces (perf
  counters, the degradation ledger, audit compile counts, engine/pool
  live state, journal fsync latency, QuantileSketch distributions),
  rendered as OpenMetrics and served over localhost ``/metrics`` +
  ``/healthz`` (``PINT_TPU_METRICS_PORT``) or dumped by
  ``pint_tpu status``.
- :mod:`pint_tpu.obs.flight` — a bounded ring of recent structured
  events (``PINT_TPU_FLIGHT_EVENTS``) that dumps itself — with the
  active spans and a metrics snapshot — to a crash report beside the
  journal on watchdog quarantine, dispatch failure, the ``serve.crash``
  drill, or SIGUSR1; ``pint_tpu recover`` prints the post-mortem.
"""

from pint_tpu.obs import flight, metrics, trace  # noqa: F401
