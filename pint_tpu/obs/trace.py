"""Request tracing: context-propagated spans over the serving stack.

The serving engine's telemetry so far is *aggregate* — stage walls
(``serve_breakdown``), bounded latency sketches, counters. None of it
answers the operator question *what happened to request X*: which lane
it queued in, how long it waited, whether the dispatch that served it
had to compile a program, which degradations fired while it was in
flight. This module is the per-request instrument:

- :func:`new_trace_id` mints a trace id; ``ServingEngine.submit`` stamps
  it on the :class:`~pint_tpu.serve.engine.ServeTicket` AND on the
  write-ahead journal record, so a request is joinable across the live
  engine, the trace buffer, and the durable store.
- :func:`attach` sets the calling thread's current trace (the worker
  attaches the dispatching batch's primary trace), so any
  :func:`span` opened underneath — the session append, a
  ``TimedProgram`` compile or ``.aotx`` deserialize (ops/compile.py) —
  is attributed to the request that triggered it.
- :func:`span` is a timed context manager; :func:`emit` writes a
  synthetic span directly (the engine reconstructs each request's
  ``request``/``admit``/``queue``/``solve`` spans from its SLO stamps at
  finalize, so the named spans cover the request's whole wall — the
  attribution-contract pattern, per request).
- Spans export as JSON Lines to a **bounded** on-disk buffer (one
  rotation generation kept) plus a bounded in-memory tail, so a
  long-lived process never grows its trace footprint.

Zero-cost when off: ``PINT_TPU_TRACE`` unset/``0`` makes :func:`span`
return one shared no-op context manager and :func:`emit` a single
boolean check — the serve path stays exactly as fast as before.
``PINT_TPU_TRACE=1`` writes under ``<cache_root>/traces``; any other
value is the output directory. :func:`configure` is the programmatic
override (bench/tests).

Coverage contract: :func:`coverage` computes, per trace, the fraction
of the ``request`` root span's wall covered by its named child spans;
the serve smoke bench locks ``coverage_min >= 0.9`` for every request
(tests/test_serve.py, tests/test_obs.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from pathlib import Path

from pint_tpu.utils import knobs

__all__ = [
    "active_spans", "attach", "configure", "coverage", "coverage_summary",
    "current_span_id", "current_trace_id", "emit", "enabled",
    "new_trace_id", "read_trace_file", "records", "reset", "span",
    "trace_dir",
]

#: on-disk buffer bound: the live JSONL file rotates to ``<name>.1`` at
#: this size and ONE predecessor generation is kept — total trace disk
#: footprint is bounded at ~2x this regardless of uptime
MAX_FILE_BYTES = 8 << 20
#: in-memory record tail (coverage / crash reports read this, not disk)
TAIL_KEEP = 4096

_lock = threading.Lock()
_tls = threading.local()      # .trace: str | None, .stack: list[str]
#: programmatic overrides (None = follow the PINT_TPU_TRACE knob)
_state: dict = {"enable": None, "dir": None}
#: the bounded in-memory tail of emitted span records
_tail: deque = deque(maxlen=TAIL_KEEP)
#: currently-open live spans: id(obj) -> record-in-progress (the flight
#: recorder snapshots this into crash reports)
_open: dict[int, dict] = {}
_seq = [0]
_file_state: dict = {"path": None, "fh": None, "bytes": 0}


def enabled() -> bool:
    """True when spans record (programmatic override, else the knob)."""
    if _state["enable"] is not None:
        return bool(_state["enable"])
    v = knobs.get("PINT_TPU_TRACE")
    return bool(v) and v != "0"


def configure(enable: bool | None = None, dir: str | os.PathLike | None = None
              ) -> None:
    """Programmatic override of the knob (None = follow the env). A dir
    change closes the current buffer file; records already in the
    in-memory tail are kept."""
    with _lock:
        _state["enable"] = enable
        _state["dir"] = None if dir is None else str(dir)
        _close_file_locked()


def trace_dir() -> Path:
    """Where span records are written (knob value when it is a path,
    else ``<cache_root>/traces``)."""
    if _state["dir"] is not None:
        return Path(_state["dir"])
    v = knobs.get("PINT_TPU_TRACE")
    if v and v not in ("0", "1"):
        return Path(v)
    from pint_tpu.utils.cache import cache_root

    return cache_root() / "traces"


def reset() -> None:
    """Drop the in-memory tail + open-span registry and close the
    buffer file (test isolation; the knob/override is untouched)."""
    with _lock:
        _tail.clear()
        _open.clear()
        _close_file_locked()


# -- ids + context -----------------------------------------------------------------


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id."""
    return uuid.uuid4().hex[:16]


def _next_span_id() -> str:
    with _lock:
        _seq[0] += 1
        return f"s{_seq[0]:x}"


def current_trace_id() -> str | None:
    """The calling thread's attached trace id (None outside a request)."""
    return getattr(_tls, "trace", None)


def current_span_id() -> str | None:
    """The innermost open span id on this thread (None outside spans)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class _Attach:
    __slots__ = ("trace", "_prev")

    def __init__(self, trace_id):
        self.trace = trace_id

    def __enter__(self):
        self._prev = getattr(_tls, "trace", None)
        if self.trace is not None:
            _tls.trace = self.trace
        return self

    def __exit__(self, *exc):
        _tls.trace = self._prev
        return False


def attach(trace_id: str | None):
    """Context manager setting this thread's current trace id (the
    cross-thread propagation hook: the engine worker attaches the
    batch's primary trace around a dispatch). ``None`` is a no-op
    attach, so call sites need no conditional."""
    return _Attach(trace_id)


# -- the span API ------------------------------------------------------------------


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "rec", "_t0")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        sid = _next_span_id()
        self.rec = {
            "trace": getattr(_tls, "trace", None),
            "span": sid,
            "parent": stack[-1] if stack else None,
            "name": self.name,
            "t0": time.time(),
            "thread": threading.current_thread().name,
        }
        if self.attrs:
            self.rec.update(self.attrs)
        stack.append(sid)
        with _lock:
            _open[id(self)] = self.rec
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        _tls.stack.pop()
        rec = dict(self.rec)
        rec["dur_ms"] = round(dur * 1e3, 4)
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        with _lock:
            _open.pop(id(self), None)
        _write(rec)
        return False


def span(name: str, **attrs):
    """Timed, nestable span on the current thread's trace. Returns one
    shared no-op object when tracing is off — the zero-cost contract."""
    if not enabled():
        return _NULL
    return _Span(name, attrs)


def emit(name: str, t0: float, dur_s: float, *, trace: str | None = None,
         span_id: str | None = None, parent: str | None = None,
         **attrs) -> None:
    """Write one synthetic span record directly (no context manager):
    the engine reconstructs per-request ``request``/``admit``/``queue``/
    ``solve`` spans from its SLO stamps at finalize. ``t0``/``dur_s``
    may come from any one consistent clock — coverage only ever compares
    durations within a trace."""
    if not enabled():
        return
    rec = {
        "trace": trace if trace is not None else getattr(_tls, "trace", None),
        "span": span_id if span_id is not None else _next_span_id(),
        "parent": parent,
        "name": name,
        "t0": float(t0),
        "dur_ms": round(max(float(dur_s), 0.0) * 1e3, 4),
    }
    if attrs:
        rec.update(attrs)
    _write(rec)


# -- the bounded buffer ------------------------------------------------------------


def _close_file_locked() -> None:
    fh = _file_state["fh"]
    if fh is not None:
        try:
            fh.close()
        except OSError:  # pragma: no cover — close race on teardown  # jaxlint: disable=silent-except — buffer close failure only affects trace flushing, never results
            pass
    _file_state.update(path=None, fh=None, bytes=0)


def _file_locked():
    """The live JSONL file handle (opened lazily; None when the trace
    dir is unwritable — the in-memory tail still records)."""
    if _file_state["fh"] is not None:
        return _file_state["fh"]
    try:
        d = trace_dir()
        d.mkdir(parents=True, exist_ok=True)
        path = d / f"trace-{os.getpid()}.jsonl"
        fh = open(path, "ab")
        _file_state.update(path=path, fh=fh, bytes=path.stat().st_size)
        return fh
    except OSError:  # jaxlint: disable=silent-except — an unwritable trace dir degrades to memory-only tracing; spans still serve coverage/crash reports from the tail
        _file_state.update(path=None, fh=None, bytes=0)
        return None


def _write(rec: dict) -> None:
    line = (json.dumps(rec, separators=(",", ":")) + "\n").encode()
    with _lock:
        _tail.append(rec)
        fh = _file_locked()
        if fh is None:
            return
        try:
            fh.write(line)
            fh.flush()
            _file_state["bytes"] += len(line)
            if _file_state["bytes"] >= MAX_FILE_BYTES:
                # bounded on disk: rotate, keeping ONE predecessor
                path = _file_state["path"]
                fh.close()
                os.replace(path, path.with_suffix(path.suffix + ".1"))
                _file_state.update(fh=open(path, "ab"), bytes=0)
        except OSError:  # jaxlint: disable=silent-except — a failed trace write degrades to memory-only tracing, never breaks the serve path
            _close_file_locked()


def records() -> list[dict]:
    """Snapshot of the in-memory record tail (newest last)."""
    with _lock:
        return list(_tail)


def active_spans() -> list[dict]:
    """Currently-open live spans with their age — what a crash report
    captures as 'what was in flight when it died'."""
    now = time.time()
    with _lock:
        snap = [dict(rec) for rec in _open.values()]
    for rec in snap:
        rec["open_ms"] = round(max(now - rec["t0"], 0.0) * 1e3, 3)
    return snap


def read_trace_file(path: str | os.PathLike) -> list[dict]:
    """Parse one JSONL trace file (malformed lines are skipped — a
    torn final line is expected crash debris)."""
    out = []
    for line in Path(path).read_bytes().splitlines():
        try:
            out.append(json.loads(line))
        except ValueError:  # jaxlint: disable=silent-except — a torn trailing line is expected crash debris; whole records all parse
            continue
    return out


# -- the per-request coverage contract ---------------------------------------------


def coverage(recs: list[dict] | None = None) -> dict[str, float]:
    """Per-trace attribution: for every trace with a ``request`` root
    span, the fraction of the root's wall covered by its direct named
    child spans (clamped to 1.0). The serve contract requires >= 0.9
    for every request."""
    recs = records() if recs is None else recs
    roots: dict[str, dict] = {}
    child_ms: dict[str, float] = {}
    for r in recs:
        t = r.get("trace")
        if not t or "dur_ms" not in r:
            continue
        if r.get("name") == "request" and "error" not in r:
            # failed requests close their root with an error attr and no
            # children — the coverage contract binds on served requests
            roots[t] = r
    for r in recs:
        t = r.get("trace")
        root = roots.get(t)
        if root is None or r.get("parent") != root["span"]:
            continue
        child_ms[t] = child_ms.get(t, 0.0) + float(r["dur_ms"])
    out = {}
    for t, root in roots.items():
        wall = float(root["dur_ms"])
        if wall <= 0.0:
            out[t] = 1.0
        else:
            out[t] = min(child_ms.get(t, 0.0) / wall, 1.0)
    return out


def coverage_summary(recs: list[dict] | None = None) -> dict:
    """JSON-ready coverage block: request count, min/mean coverage."""
    cov = coverage(recs)
    vals = sorted(cov.values())
    return {
        "requests_traced": len(vals),
        "coverage_min": round(vals[0], 4) if vals else None,
        "coverage_mean": (round(sum(vals) / len(vals), 4) if vals else None),
    }
