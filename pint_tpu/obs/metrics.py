"""Process-global metrics registry + OpenMetrics export.

Every number the serving stack already measures — perf counters
(ops/perf.py), degradation-ledger kinds (ops/degrade.py), audit
compile counts, serve lane depths, pool occupancy, journal fsync
latency, the bounded :class:`~pint_tpu.ops.perf.QuantileSketch`
latency distributions — lives inside one Python process and dies with
it. This module is the export surface: a process-global
:class:`MetricsRegistry` that those surfaces *feed* (they stay the
single source of truth — nothing is measured twice), rendered as an
OpenMetrics text snapshot by :meth:`MetricsRegistry.render` and served
by a stdlib HTTP endpoint (:class:`MetricsServer`: ``/metrics`` +
``/healthz``, localhost, knob ``PINT_TPU_METRICS_PORT``) or dumped
one-shot by ``pint_tpu status``.

Feeding, not duplicating:

- ``perf.add`` forwards every counter bump through the
  :func:`feed_counter` hook (``perf.set_metrics_feed``); only counters
  registered here (the :data:`COUNTER_HELP` inventory) are exported —
  and the **no-orphan gate** (tests/test_obs.py) walks every
  ``serve_*``/``incremental_*`` ``perf.add`` call site in the source
  and fails when one is missing from the inventory, so a new signal
  cannot silently bypass export.
- ``degrade.record`` feeds the ``pint_tpu_degradations_total{kind=…}``
  labeled counter through the ledger's observer hook; the label set is
  the registered taxonomy (``degrade.KINDS``) by construction.
- Gauges take a callback (``fn=``) so live state — queue depth, pool
  occupancy, quarantined lanes — is read at scrape time from the
  owning object, never mirrored. Re-registering a gauge replaces its
  callback (the newest engine wins).
- Histograms wrap a :class:`~pint_tpu.ops.perf.QuantileSketch`
  (bounded memory, mergeable) and render as OpenMetrics summaries;
  :meth:`MetricsRegistry.summary` exports an externally-owned sketch
  (the engine's latency distributions) the same way.

The registry is created (and all hooks installed) on the first
:func:`registry` call — a process that never touches the serving or
observability surfaces pays nothing.
"""

from __future__ import annotations

import json
import re
import threading

from pint_tpu.ops import perf
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.obs")

__all__ = [
    "COUNTER_HELP", "MetricsRegistry", "MetricsServer", "feed_counter",
    "observe", "parse_openmetrics", "registry", "reset_registry",
]

#: every metric exports under this prefix (OpenMetrics namespacing)
PREFIX = "pint_tpu_"

#: the explicit counter inventory: every ``serve_*``/``incremental_*``
#: perf counter the telemetry layer bumps, with its export help line.
#: The no-orphan gate (tests/test_obs.py) greps the source for
#: ``perf.add("serve_…")``/``perf.add("incremental_…")`` call sites and
#: fails when one is missing here — registration is a contract, not a
#: convention.
COUNTER_HELP: dict[str, str] = {
    "serve_requests": "requests admitted by the serving engine",
    "serve_shed": "requests refused or dropped by admission control",
    "serve_dispatches": "batches dispatched to the device",
    "serve_coalesced": "requests answered by a shared coalesced solve",
    "serve_appends": "append requests served",
    "serve_refits": "refit requests served",
    "serve_evictions": "warm sessions evicted from the pool",
    "serve_restores": "sessions restored from checkpoints",
    "serve_journal_records": "write-ahead journal records appended",
    "serve_journal_compactions": "journal checkpoint compactions",
    "serve_journal_full": "journal writes shed on ENOSPC (disk full)",
    "serve_checkpoints": "fleet session checkpoints written",
    "serve_deadline_expired": "queued requests shed past their deadline",
    "serve_retries": "transiently failed dispatches retried",
    "serve_quarantines": "sessions quarantined by the watchdog/crash-loop detector",
    "serve_worker_replacements": "hung workers abandoned and replaced",
    "serve_migrations": "live sessions migrated between replicas",
    "serve_replicas_lost": "replica processes lost and absorbed by survivors",
    "serve_gateway_requests": "requests proxied by the fleet gateway",
    "serve_gateway_shed": "gateway requests refused with 429/503",
    "incremental_refits": "appends answered by the rank-k incremental path",
    "incremental_fallbacks": "appends that fell back to the full warm refit",
    "incremental_rows_appended": "TOA rows appended into resident sessions",
    # durable-campaign telemetry (pint_tpu/campaign/runner.py); the live
    # progress gauges (campaign_units_done/total, checkpoint age, ETA)
    # register with fn= callbacks when a CampaignRunner exists
    "campaign_units_run": "campaign work units executed to a durable result",
    "campaign_checkpoints": "campaign progress snapshots written",
    "campaign_resumes": "campaigns resumed from durable checkpoints",
}


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str):
        self.name = _sanitize(name)
        self.help = help

    def head(self) -> list[str]:
        full = PREFIX + self.name
        return [f"# HELP {full} {self.help}", f"# TYPE {full} {self.kind}"]


class Counter(_Metric):
    """Monotone counter; ``fn`` makes it a live read-through to an
    existing process-global count (the feeding surface stays the source
    of truth)."""

    kind = "counter"

    def __init__(self, name, help, fn=None):
        super().__init__(name, help)
        self.fn = fn
        self._v = 0.0

    def inc(self, v: float = 1.0) -> None:
        self._v += v

    @property
    def value(self) -> float:
        return float(self.fn()) if self.fn is not None else self._v

    def samples(self) -> list[str]:
        return [f"{PREFIX}{self.name}_total {self.value:g}"]


class Gauge(_Metric):
    """Point-in-time value; ``fn`` reads live state at scrape time."""

    kind = "gauge"

    def __init__(self, name, help, fn=None):
        super().__init__(name, help)
        self.fn = fn
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    @property
    def value(self) -> float:
        if self.fn is None:
            return self._v
        try:
            return float(self.fn())
        except Exception:  # jaxlint: disable=silent-except — a dead gauge callback (its engine was stopped) scrapes as 0 rather than failing the whole /metrics page
            return 0.0

    def samples(self) -> list[str]:
        return [f"{PREFIX}{self.name} {self.value:g}"]


class LabeledCounter(_Metric):
    """One counter family with a single label dimension (the
    degradation taxonomy: ``…_total{kind="serve.shed"}``)."""

    kind = "counter"

    def __init__(self, name, help, label: str):
        super().__init__(name, help)
        self.label = label
        self._v: dict[str, float] = {}
        # the degrade observer feeds from whatever thread degraded; an
        # unlocked read-modify-write would lose bumps under contention
        self._vlock = threading.Lock()

    def inc(self, label_value: str, v: float = 1.0) -> None:
        with self._vlock:
            self._v[label_value] = self._v.get(label_value, 0.0) + v

    def samples(self) -> list[str]:
        with self._vlock:
            items = sorted(self._v.items())
        return [
            f'{PREFIX}{self.name}_total{{{self.label}="{lv}"}} {val:g}'
            for lv, val in items
        ]


class Summary(_Metric):
    """Quantile summary over a bounded :class:`~pint_tpu.ops.perf.
    QuantileSketch` — registry-owned (``observe``) or wrapping an
    externally-owned sketch (the engine's latency distributions)."""

    kind = "summary"

    def __init__(self, name, help, sketch=None):
        super().__init__(name, help)
        self.sketch = sketch if sketch is not None else perf.QuantileSketch()

    def observe(self, v: float) -> None:
        self.sketch.add(v)

    def samples(self) -> list[str]:
        full = PREFIX + self.name
        out = []
        for q in (0.5, 0.9, 0.99):
            v = self.sketch.quantile(q)
            if v is not None:
                out.append(f'{full}{{quantile="{q:g}"}} {v:g}')
        with self.sketch._lock:
            n, s = self.sketch._n, self.sketch._sum
        out.append(f"{full}_count {n}")
        out.append(f"{full}_sum {s:g}")
        return out


class MetricsRegistry:
    """Name -> metric, rendered as one OpenMetrics text snapshot."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -- registration (get-or-create; gauges replace their callback) ------------

    def counter(self, name: str, help: str, fn=None) -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name, help, fn=fn)
            return m

    def gauge(self, name: str, help: str, fn=None) -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name, help, fn=fn)
            elif fn is not None:
                m.fn = fn              # the newest owner wins (engine churn)
            return m

    def labeled_counter(self, name: str, help: str, label: str
                        ) -> LabeledCounter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = LabeledCounter(name, help, label)
            return m

    def summary(self, name: str, help: str, sketch=None) -> Summary:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Summary(name, help, sketch=sketch)
            elif sketch is not None:
                m.sketch = sketch
            return m

    # -- feeding -----------------------------------------------------------------

    def feed(self, name: str, value: float) -> None:
        """One perf-counter bump: exported iff the name is registered
        (the COUNTER_HELP inventory); anything else is not a serve/
        incremental export signal and is ignored."""
        m = self._metrics.get(name)
        if isinstance(m, Counter) and m.fn is None:
            with self._lock:
                m.inc(value)

    def observe(self, name: str, value: float) -> None:
        m = self._metrics.get(name)
        if isinstance(m, Summary):
            m.observe(value)

    # -- introspection -----------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def render(self) -> str:
        """The OpenMetrics text snapshot (``# HELP``/``# TYPE`` heads,
        samples, terminating ``# EOF``)."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.head())
            lines.extend(m.samples())
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


# -- the process-global registry + hooks -------------------------------------------

_registry: MetricsRegistry | None = None
_reg_lock = threading.Lock()


def feed_counter(name: str, value: float) -> None:
    """The ``perf.add`` forwarding hook (installed by :func:`registry`)."""
    reg = _registry
    if reg is not None:
        reg.feed(name, value)


def observe(name: str, value: float) -> None:
    """Feed one observation into a registered summary (e.g. the journal
    fsync latency). No-op until the registry exists — a process that
    never scrapes pays nothing."""
    reg = _registry
    if reg is not None:
        reg.observe(name, value)


def _on_degrade(event) -> None:
    reg = _registry
    if reg is not None:
        reg.labeled_counter(
            "degradations",
            "graceful-degradation ledger events by kind (ops/degrade.py)",
            "kind").inc(event.kind)


def _install(reg: MetricsRegistry) -> None:
    """Register the standard export set and wire the feeding hooks."""
    for name, help in COUNTER_HELP.items():
        reg.counter(name, help)
    reg.labeled_counter(
        "degradations",
        "graceful-degradation ledger events by kind (ops/degrade.py)",
        "kind")
    reg.summary("serve_journal_fsync_seconds",
                "write-ahead journal fsync latency in seconds")

    from pint_tpu.utils import logging as plog

    reg.counter("log_suppressed", "log records suppressed by the dedup "
                "filter / log_once (survives handler re-init)",
                fn=plog.suppressed_total)

    def _compiles():
        from pint_tpu.analysis.jaxpr_audit import compile_count

        return compile_count()

    reg.counter("program_compiles",
                "TimedProgram trace+compile events (audit ledger)",
                fn=_compiles)

    def _aot(field):
        def read():
            from pint_tpu.ops.compile import aot_block

            return aot_block()[field]
        return read

    reg.counter("aot_deserialize_hits",
                "programs served by a deserialized .aotx executable",
                fn=_aot("deserialize_hits"))
    reg.counter("aot_deserialize_misses",
                "artifact-store probes that fell back to trace+compile",
                fn=_aot("deserialize_misses"))

    perf.set_metrics_feed(feed_counter)
    from pint_tpu.ops import degrade

    degrade.add_observer(_on_degrade)


def registry() -> MetricsRegistry:
    """The process-global registry, created (and hooks installed) on
    first use."""
    global _registry
    with _reg_lock:
        if _registry is None:
            reg = MetricsRegistry()
            _install(reg)
            _registry = reg
        return _registry


def reset_registry() -> None:
    """Replace the registry with a fresh installed one (test isolation;
    the perf/degrade hooks keep pointing at the module global)."""
    global _registry
    with _reg_lock:
        reg = MetricsRegistry()
        _install(reg)
        _registry = reg


# -- OpenMetrics parsing (the bench/test validator) --------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(-?[0-9.eE+infa]+)$")
_COMMENT_RE = re.compile(r"^# (HELP|TYPE|UNIT) ([a-zA-Z_:][a-zA-Z0-9_:]*) ?")


def parse_openmetrics(text: str) -> tuple[dict[str, float], set[str]]:
    """Strict-enough OpenMetrics validation for the bench/test
    contract: every line must be a HELP/TYPE/UNIT comment, a sample, or
    the terminating ``# EOF``. Returns ``(samples, families)`` where
    ``samples`` maps the full sample key (name + label set) to its
    value and ``families`` is the set of declared metric names.
    Raises ``ValueError`` on any malformed line or a missing EOF."""
    samples: dict[str, float] = {}
    families: set[str] = set()
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("OpenMetrics text must end with '# EOF'")
    for ln in lines[:-1]:
        if not ln:
            continue
        m = _COMMENT_RE.match(ln)
        if m:
            families.add(m.group(2))
            continue
        m = _SAMPLE_RE.match(ln)
        if m is None:
            raise ValueError(f"malformed OpenMetrics line: {ln!r}")
        samples[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return samples, families


# -- the HTTP endpoint --------------------------------------------------------------


class MetricsServer:
    """Localhost ``/metrics`` + ``/healthz`` over stdlib http.server.

    ``health_fn`` returns ``(ok, detail_dict)``; ``/healthz`` answers
    200/503 with the JSON detail. ``port=0`` binds an ephemeral port
    (read it back from :attr:`port`). The server thread is a daemon —
    it never blocks interpreter exit."""

    def __init__(self, reg: MetricsRegistry | None = None, port: int = 0,
                 health_fn=None):
        self.reg = reg if reg is not None else registry()
        self.health_fn = health_fn
        self._httpd = None
        self._thread = None
        self.port = int(port)

    def start(self) -> int:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 — silence stdlib access logs
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib handler API
                if self.path.split("?")[0] == "/metrics":
                    body = server.reg.render().encode()
                    self._send(200, body,
                               "application/openmetrics-text; "
                               "version=1.0.0; charset=utf-8")
                    return
                if self.path.split("?")[0] == "/healthz":
                    ok, detail = (True, {}) if server.health_fn is None \
                        else server.health_fn()
                    body = json.dumps(
                        dict(detail, ok=bool(ok))).encode()
                    self._send(200 if ok else 503, body,
                               "application/json")
                    return
                self._send(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pint-tpu-metrics",
            daemon=True)
        self._thread.start()
        log.info(f"metrics endpoint serving on 127.0.0.1:{self.port} "
                 "(/metrics, /healthz)")
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
