"""Flight recorder: the last N structured events, dumped on a crash.

When the watchdog quarantines a lane, a dispatch dies with an unhandled
exception, or the ``serve.crash`` drill kills the process, the
aggregate telemetry says *that* something happened — the operator needs
the last ten seconds of *what*: the dispatches in flight, the sheds and
evictions leading up to it, the degradations that fired, the compiles a
request triggered. This module keeps that story in a bounded in-memory
ring (:class:`FlightRecorder`, size ``PINT_TPU_FLIGHT_EVENTS``) that
every serving surface feeds:

- ledger degradations (sheds, evictions, deadline expiries, retries,
  quarantines, journal truncation/corruption, host fallbacks) arrive
  through the ``ops/degrade.py`` observer hook — registered at import,
  so ANY degradation anywhere lands in the ring with its trace id;
- the engine notes dispatches + watchdog beats, the journal notes
  checkpoints, the pool notes restores, ``TimedProgram`` notes
  compile / ``.aotx`` deserialize events (ops/compile.py).

On trigger — watchdog quarantine, exhausted dispatch retries, the
``serve.crash`` fault, or ``SIGUSR1`` — :func:`dump_crash_report`
writes one JSON **crash report** beside the journal store
(``<durable_dir>/crash/``): the ring snapshot, the currently-open trace
spans (what was in flight), an OpenMetrics snapshot, and the
degradation block. ``pint_tpu recover`` picks the newest report up and
prints the post-mortem summary (:func:`summarize_crash_report`).

Event notes are a lock + deque append of a small dict: cheap enough to
leave on everywhere; ``PINT_TPU_FLIGHT_EVENTS=0`` disables recording.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from pint_tpu.ops import degrade
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.obs")

__all__ = [
    "FlightRecorder", "crash_report", "dump_crash_report",
    "install_signal_handler", "latest_report", "note", "recorder",
    "summarize_crash_report",
]


class FlightRecorder:
    """Bounded ring of recent structured events (thread-safe)."""

    def __init__(self, maxlen: int | None = None):
        if maxlen is None:
            maxlen = int(knobs.get("PINT_TPU_FLIGHT_EVENTS") or 0)
        self.maxlen = int(maxlen)
        self._ring: deque = deque(maxlen=max(self.maxlen, 1))
        self._lock = threading.Lock()
        self._seq = 0
        self.total = 0                 # events ever noted (ring evicts)

    def note(self, kind: str, **fields) -> None:
        if self.maxlen <= 0:
            return
        rec = {"kind": kind, "t": time.time(),
               "t_mono": time.monotonic()}
        if fields:
            rec.update(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            self.total += 1

    def snapshot(self) -> list[dict]:
        """The ring contents, oldest first."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_recorder: FlightRecorder | None = None
_rec_lock = threading.Lock()


def recorder() -> FlightRecorder:
    """The process-global ring (created on first use; ring size reads
    ``PINT_TPU_FLIGHT_EVENTS`` at creation)."""
    global _recorder
    with _rec_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def reset_recorder() -> None:
    """Fresh ring (test isolation; re-reads the size knob)."""
    global _recorder
    with _rec_lock:
        _recorder = None


def note(kind: str, **fields) -> None:
    """Append one event to the process ring."""
    recorder().note(kind, **fields)


def _on_degrade(event) -> None:
    note("degrade", degrade_kind=event.kind, component=event.component,
         detail=event.detail, trace=event.trace_id, count=event.count)


# every ledger write anywhere in the process lands in the ring — the
# crash report's core narrative (sheds, evictions, fallbacks, journal
# damage) comes for free from the taxonomy
degrade.add_observer(_on_degrade)


# -- crash reports ------------------------------------------------------------------


def crash_report(reason: str, extra: dict | None = None) -> dict:
    """Assemble the post-mortem payload: ring events + active trace
    spans + an OpenMetrics snapshot + the degradation block."""
    from pint_tpu.obs import metrics, trace

    rep = {
        "reason": reason,
        "pid": os.getpid(),
        "t": time.time(),
        "events": recorder().snapshot(),
        "events_total": recorder().total,
        "active_spans": trace.active_spans(),
        "metrics": metrics.registry().render(),
        "degradations": degrade.degradation_block(),
    }
    if extra:
        rep.update(extra)
    return rep


def dump_crash_report(dirpath: str | os.PathLike, reason: str,
                      extra: dict | None = None) -> Path | None:
    """Write one crash report under ``<dirpath>/`` (the engine passes
    its ``<durable_dir>/crash`` directory — beside the journal store).
    Returns the path, or None when the directory is unwritable (a crash
    report must never turn a degradation into a crash)."""
    try:
        d = Path(dirpath)
        d.mkdir(parents=True, exist_ok=True)
        rep = crash_report(reason, extra=extra)
        path = d / f"crash-{os.getpid()}-{int(time.time() * 1e3)}.json"
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(rep, indent=1, default=str))
        tmp.replace(path)
        log.error(f"flight recorder: crash report written to {path} "
                  f"({len(rep['events'])} ring events, "
                  f"{len(rep['active_spans'])} active spans) — {reason}")
        return path
    except Exception as e:  # noqa: BLE001  # jaxlint: disable=silent-except — crash-report writing is best-effort telemetry on an already-failing path; the failure itself is logged
        log.error(f"flight recorder: could not write crash report: {e}")
        return None


def latest_report(dirpath: str | os.PathLike) -> Path | None:
    """The newest crash report under ``<dirpath>/crash/`` (or under
    ``<dirpath>`` itself), None when there is none — what
    ``pint_tpu recover`` summarizes."""
    for d in (Path(dirpath) / "crash", Path(dirpath)):
        if d.is_dir():
            reports = sorted(d.glob("crash-*.json"),
                             key=lambda p: p.stat().st_mtime)
            if reports:
                return reports[-1]
    return None


def summarize_crash_report(path: str | os.PathLike) -> str:
    """Human-readable post-mortem: the reason, the active spans at the
    moment of death, the last ring events and the degradation kinds —
    what ``pint_tpu recover`` prints when it finds a report."""
    rep = json.loads(Path(path).read_text())
    lines = [
        f"crash report {Path(path).name}",
        f"  reason: {rep.get('reason')}",
        f"  pid {rep.get('pid')} at {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(rep.get('t', 0)))}",
    ]
    spans = rep.get("active_spans") or []
    lines.append(f"  in flight when it died: {len(spans)} span(s)")
    for s in spans[:8]:
        lines.append(
            f"    {s.get('name')} (trace {s.get('trace')}) open "
            f"{s.get('open_ms', 0):.0f} ms")
    degr = rep.get("degradations") or {}
    if degr.get("kinds"):
        lines.append(f"  degradations: {', '.join(degr['kinds'])}")
    events = rep.get("events") or []
    lines.append(f"  last {min(len(events), 10)} of {len(events)} ring "
                 "event(s):")
    for ev in events[-10:]:
        detail = ev.get("degrade_kind") or ev.get("label") or \
            ev.get("lane") or ev.get("session") or ""
        lines.append(f"    [{ev.get('seq')}] {ev.get('kind')} {detail}")
    if rep.get("metrics"):
        n = sum(1 for ln in rep["metrics"].splitlines()
                if ln.startswith("# TYPE"))
        lines.append(f"  metrics snapshot: {n} families (in the report)")
    return "\n".join(lines)


# -- SIGUSR1 ------------------------------------------------------------------------

_signal_state: dict = {"installed_for": None}


def install_signal_handler(dirpath: str | os.PathLike) -> bool:
    """Dump a crash report to ``dirpath`` on ``SIGUSR1`` — the
    live-process inspection hook (``kill -USR1 <pid>``). Returns False
    when signals cannot be installed from this thread (only the main
    thread may set handlers — a worker-thread engine start skips it)."""
    import signal

    def _dump(signum, frame):  # noqa: ARG001 — signal handler signature
        dump_crash_report(dirpath, f"signal {signum} (operator request)")

    try:
        signal.signal(signal.SIGUSR1, _dump)
    except (ValueError, OSError, AttributeError):  # jaxlint: disable=silent-except — non-main-thread/platform without SIGUSR1: the on-demand dump is unavailable, every crash-triggered dump still works
        return False
    _signal_state["installed_for"] = str(dirpath)
    return True
