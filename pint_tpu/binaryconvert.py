"""Conversion between binary model parameterizations.

Reference: pint/binaryconvert.py (convert_binary:536 — ELL1<->DD/BT,
ELL1H->ELL1, parameter transformations with the standard small-eccentricity
relations). Operates in place on our TimingModel: swaps the PulsarBinary
component's engine configuration and maps the parameter set.

    ELL1 -> DD/BT:  ECC = hypot(EPS1, EPS2), OM = atan2(EPS1, EPS2),
                    T0 = TASC + OM/(2 pi) * PB
    DD/BT -> ELL1:  EPS1 = ECC sin OM, EPS2 = ECC cos OM,
                    TASC = T0 - OM/(2 pi) * PB
    ELL1H -> ELL1:  SINI = 2 STIG/(1+STIG^2), M2 = H3/(Tsun STIG^3)
"""

from __future__ import annotations

import numpy as np

from pint_tpu import SECS_PER_DAY, TSUN_S
from pint_tpu.models.base import leaf_to_f64
from pint_tpu.models.binary import PulsarBinary
from pint_tpu.models.parameter import ParamValueMeta
from pint_tpu.ops.dd import DD, device_split
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.binaryconvert")

_ECCENTRIC = ("BT", "DD", "DDS")
_ELL1_LIKE = ("ELL1", "ELL1H", "ELL1K")


def _f(model, name, default=0.0):
    v = model.params.get(name)
    return default if v is None else float(np.asarray(leaf_to_f64(v)))


def _set(model, comp, name, value, frozen=None):
    spec = comp.specs.get(name)
    if spec is None:
        raise KeyError(f"{comp.model_name} has no parameter {name}")
    if spec.kind in ("dd", "epoch"):
        hi, lo = device_split(np.float64(value), np.float64(0.0))
        model.params[name] = DD(np.float64(hi), np.float64(lo))
    else:
        model.params[name] = float(value)
    pm = model.param_meta.get(name)
    was_frozen = pm.frozen if pm is not None else True
    model.param_meta[name] = ParamValueMeta(
        spec=spec, frozen=was_frozen if frozen is None else frozen
    )


def _drop(model, *names):
    for n in names:
        model.params.pop(n, None)
        model.param_meta.pop(n, None)


def convert_binary(model, target: str):
    """In-place conversion of the model's binary to `target` (reference
    convert_binary:536). Returns the model for chaining."""
    target = target.upper()
    old = next((c for c in model.components if isinstance(c, PulsarBinary)), None)
    if old is None:
        raise ValueError("model has no binary component")
    src = old.model_name.upper()
    if src == target:
        return model

    # epoch conversions need PB in seconds
    pb_s = _f(model, "PB")
    if pb_s == 0.0 and "FB0" in model.params:
        pb_s = 1.0 / _f(model, "FB0")

    new = PulsarBinary(target)
    model.components[model.components.index(old)] = new

    if src in _ELL1_LIKE and target in _ECCENTRIC:
        eps1, eps2 = _f(model, "EPS1"), _f(model, "EPS2")
        ecc = float(np.hypot(eps1, eps2))
        om = float(np.arctan2(eps1, eps2)) % (2 * np.pi)
        tasc = model.params["TASC"]
        t0_s = float(np.asarray(tasc.hi)) + float(np.asarray(tasc.lo)) + om / (2 * np.pi) * pb_s
        _set(model, new, "ECC", ecc, frozen=model.param_meta.get("EPS1", ParamValueMeta(spec=None)).frozen)
        new_om_spec = new.specs["OM"]
        model.params["OM"] = om
        model.param_meta["OM"] = ParamValueMeta(spec=new_om_spec, frozen=model.param_meta["EPS2"].frozen)
        hi, lo = device_split(np.float64(t0_s), np.float64(0.0))
        model.params["T0"] = DD(np.float64(hi), np.float64(lo))
        model.param_meta["T0"] = ParamValueMeta(spec=new.specs["T0"], frozen=model.param_meta["TASC"].frozen)
        _drop(model, "EPS1", "EPS2", "TASC", "H3", "H4", "STIGMA", "NHARMS", "LNEDOT")
    elif src in _ECCENTRIC and target in _ELL1_LIKE:
        ecc, om = _f(model, "ECC"), _f(model, "OM")
        eps1, eps2 = ecc * np.sin(om), ecc * np.cos(om)
        t0 = model.params["T0"]
        tasc_s = float(np.asarray(t0.hi)) + float(np.asarray(t0.lo)) - om / (2 * np.pi) * pb_s
        frozen_e = model.param_meta.get("ECC", ParamValueMeta(spec=None)).frozen
        _set(model, new, "EPS1", eps1, frozen=frozen_e)
        _set(model, new, "EPS2", eps2, frozen=frozen_e)
        hi, lo = device_split(np.float64(tasc_s), np.float64(0.0))
        model.params["TASC"] = DD(np.float64(hi), np.float64(lo))
        model.param_meta["TASC"] = ParamValueMeta(spec=new.specs["TASC"], frozen=model.param_meta["T0"].frozen)
        _drop(model, "ECC", "OM", "T0", "OMDOT" if target != "ELL1K" else "", "EDOT")
    elif src == "ELL1H" and target == "ELL1":
        h3 = _f(model, "H3")
        stig = _f(model, "STIGMA")
        if stig == 0.0 and "H4" in model.params:
            stig = _f(model, "H4") / h3 if h3 else 0.0
        if stig:
            sini = 2 * stig / (1 + stig**2)
            m2 = h3 / (TSUN_S * stig**3)
            _set(model, new, "SINI", sini)
            _set(model, new, "M2", m2)
        _drop(model, "H3", "H4", "STIGMA", "NHARMS")
    elif src == "ELL1" and target == "ELL1H":
        m2, sini = _f(model, "M2"), _f(model, "SINI")
        if m2 and sini:
            c = np.sqrt(1 - sini**2)
            stig = sini / (1 + c)
            _set(model, new, "H3", TSUN_S * m2 * stig**3)
            _set(model, new, "STIGMA", stig)
        _drop(model, "M2", "SINI")
    elif src in _ECCENTRIC and target in _ECCENTRIC:
        pass  # shared eccentric parameterization (BT<->DD<->DDS)
    elif src in _ELL1_LIKE and target in _ELL1_LIKE:
        pass
    else:
        raise NotImplementedError(f"conversion {src} -> {target}")

    model.meta["BINARY"] = target
    model.clear_caches()  # jitted programs captured the old component
    # validate the new configuration
    new.validate(model.params, model.meta)
    log.info(f"converted binary {src} -> {target}")
    return model
