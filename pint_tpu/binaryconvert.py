"""Conversion between binary model parameterizations.

Reference: pint/binaryconvert.py (convert_binary:536 — any of
BT/DD/DDS/DDK/ELL1/ELL1H/ELL1k to any other; DDGR accepted as INPUT only,
"there is not a well-defined way to get a unique output" :29). Operates in
place on our TimingModel: swaps the PulsarBinary component's engine
configuration and maps the parameter set.

    ELL1 -> DD/BT:  ECC = hypot(EPS1, EPS2), OM = atan2(EPS1, EPS2),
                    T0 = TASC + OM/(2 pi) * PB
    DD/BT -> ELL1:  EPS1 = ECC sin OM, EPS2 = ECC cos OM,
                    TASC = T0 - OM/(2 pi) * PB
    ELL1H -> ELL1:  SINI = 2 STIG/(1+STIG^2), M2 = H3/(Tsun STIG^3)
    DD <-> DDS:     SHAPMAX = -ln(1 - SINI)
    DD -> DDK:      KIN = arcsin(SINI) (convention caveat as the
                    reference: 180 deg - KIN is equally valid), KOM given
    DDGR -> *:      post-Keplerian set derived from (MTOT, M2) under GR

Uncertainty propagation: the reference threads every transformation
through the `uncertainties` package; here each transform is a jax scalar
function and the output sigmas come from its autodiff jacobian (diagonal
input covariance, like the reference).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import TSUN_S
from pint_tpu.models.base import leaf_to_f64
from pint_tpu.models.binary import PulsarBinary
from pint_tpu.models.parameter import ParamValueMeta
from pint_tpu.ops.dd import DD, device_split
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.binaryconvert")

_ECCENTRIC = ("BT", "DD", "DDS", "DDK", "DDGR")
_ELL1_LIKE = ("ELL1", "ELL1H", "ELL1K")


def _f(model, name, default=0.0):
    v = model.params.get(name)
    return default if v is None else float(np.asarray(leaf_to_f64(v)))


def _u(model, name):
    pm = model.param_meta.get(name)
    return None if pm is None else pm.uncertainty


def propagate(fn, vals, uncs):
    """(outputs, output_sigmas): evaluate the jnp transform and push the
    diagonal input sigmas through its jacobian (autodiff replaces the
    reference's `uncertainties`-package bookkeeping)."""
    x = jnp.asarray([float(v) for v in vals], jnp.float64)

    def f(x):
        out = fn(*x)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return jnp.stack([jnp.asarray(v, jnp.float64) for v in out])

    y = np.asarray(f(x)).ravel()
    if not any(u is not None for u in uncs):
        return y, [None] * y.size
    J = np.asarray(jax.jacfwd(f)(x)).reshape(y.size, x.size)
    s = np.asarray([u if u is not None else 0.0 for u in uncs])
    return y, list(np.sqrt((J**2) @ s**2))


def _set(model, comp, name, value, unc=None, frozen=None):
    spec = comp.specs.get(name)
    if spec is None:
        raise KeyError(f"{comp.model_name} has no parameter {name}")
    if spec.kind in ("dd", "epoch"):
        if isinstance(value, DD):
            model.params[name] = value
        else:
            hi, lo = device_split(np.float64(value), np.float64(0.0))
            model.params[name] = DD(np.float64(hi), np.float64(lo))
    else:
        model.params[name] = float(value)
    pm = model.param_meta.get(name)
    was_frozen = pm.frozen if pm is not None else True
    model.param_meta[name] = ParamValueMeta(
        spec=spec,
        frozen=was_frozen if frozen is None else frozen,
        uncertainty=None if unc is None else float(unc),
    )


def _drop(model, *names):
    for n in names:
        if n:
            model.params.pop(n, None)
            model.param_meta.pop(n, None)


def _epoch_dd(model, name):
    v = model.params[name]
    return v if isinstance(v, DD) else DD(np.float64(float(v)), np.float64(0.0))


def _dd_shift(v: DD, shift_s: float) -> DD:
    from pint_tpu.ops.dd import dd_add_fp

    out = dd_add_fp(v, np.float64(shift_s))
    hi, lo = device_split(np.float64(np.asarray(out.hi)), np.float64(np.asarray(out.lo)))
    return DD(np.float64(hi), np.float64(lo))


def _pb_seconds(model):
    pb = _f(model, "PB")
    if pb == 0.0 and "FB0" in model.params:
        pb = 1.0 / _f(model, "FB0")
    return pb


def _ddgr_to_pk(model):
    """Materialize the GR-derived PK parameters (+ sigmas) of a DDGR model
    (reference _DDGR_to_PK, binaryconvert.py:427) via the same closed
    expressions the DDGR engine integrates (engines.ddgr_derived)."""
    from pint_tpu.models.binaries.engines import ddgr_derived

    names = ("MTOT", "M2", "ECC", "A1", "PB", "XOMDOT")
    keys = ("OMDOT", "GAMMA", "PBDOT", "SINI", "DR", "DTH")

    def fn(mtot, m2, ecc, a1, pb, xomdot):
        d = ddgr_derived({
            "MTOT": mtot, "M2": m2, "ECC": ecc, "A1": a1, "PB": pb,
            "XOMDOT": xomdot,
        })
        return tuple(d[k] for k in keys)

    vals, uncs = propagate(
        fn, [_f(model, n) for n in names], [_u(model, n) for n in names]
    )
    return dict(zip(keys, zip(vals, uncs)))


def convert_binary(model, target: str, kom_deg: float = 0.0):
    """In-place conversion of the model's binary to `target` (reference
    convert_binary:536). `kom_deg` seeds KOM for a DDK target. Returns the
    model for chaining."""
    target = target.upper()
    old = next((c for c in model.components if isinstance(c, PulsarBinary)), None)
    if old is None:
        raise ValueError("model has no binary component")
    src = old.model_name.upper()
    if src == target:
        return model
    if target == "DDGR":
        raise NotImplementedError(
            "DDGR output is not well-defined (reference binaryconvert.py:29)"
        )

    pb_s = _pb_seconds(model)
    new = PulsarBinary(target)
    model.components[model.components.index(old)] = new

    # --- DDGR input: materialize its PK set first, then treat as DD ----------
    if src == "DDGR":
        pk = _ddgr_to_pk(model)
        xpbdot, s_xpbdot = _f(model, "XPBDOT", 0.0), _u(model, "XPBDOT")
        _drop(model, "MTOT", "XOMDOT", "XPBDOT")
        src = "DD"
        for k in ("OMDOT", "GAMMA", "PBDOT", "SINI", "DR", "DTH"):
            v, s = pk[k]
            if k in new.specs:
                _set(model, new, k, v, unc=s, frozen=True)
            elif k == "SINI":
                # not in a DDS/DDK target's spec table: stage it for
                # _retarget_incl to map to SHAPMAX/KIN
                from pint_tpu.models.parameter import ParamSpec

                model.params[k] = float(v)
                model.param_meta[k] = ParamValueMeta(
                    spec=ParamSpec(k, unit=""), frozen=True, uncertainty=s,
                )
            else:
                log.warning(
                    f"DDGR-derived {k} = {float(v):.3e} has no slot in "
                    f"BINARY {target}; dropped"
                )
        if xpbdot and "XPBDOT" in new.specs:
            # the engine applied PBDOT_GR + XPBDOT; the target carries the
            # excess explicitly (every model's common specs include it)
            _set(model, new, "XPBDOT", xpbdot, unc=s_xpbdot, frozen=True)

    # --- eccentric <-> ELL1-like --------------------------------------------
    if src in _ECCENTRIC and target in _ELL1_LIKE:
        ecc, om = _f(model, "ECC"), _f(model, "OM")
        (eps1, eps2), (s1, s2) = propagate(
            lambda e, w: (e * jnp.sin(w), e * jnp.cos(w)),
            [ecc, om], [_u(model, "ECC"), _u(model, "OM")],
        )
        # reference: EPS frozen if EITHER source param is (binaryconvert)
        frozen_e = (
            model.param_meta.get("ECC", ParamValueMeta(spec=None)).frozen
            or model.param_meta.get("OM", ParamValueMeta(spec=None)).frozen
        )
        _set(model, new, "EPS1", eps1, unc=s1, frozen=frozen_e)
        _set(model, new, "EPS2", eps2, unc=s2, frozen=frozen_e)
        tasc = _dd_shift(_epoch_dd(model, "T0"), -om / (2 * np.pi) * pb_s)
        # sigma(TASC) from the (T0, OM, PB) jacobian
        _, (st,) = propagate(
            lambda t0, w, pb: t0 - w / (2 * jnp.pi) * pb,
            [0.0, om, pb_s],
            [_u(model, "T0"), _u(model, "OM"), _u(model, "PB")],
        )
        model.params["TASC"] = tasc
        model.param_meta["TASC"] = ParamValueMeta(
            spec=new.specs["TASC"],
            frozen=model.param_meta["T0"].frozen,
            uncertainty=st,
        )
        _drop(model, "ECC", "OM", "T0", "EDOT",
              "OMDOT" if target != "ELL1K" else "")
        _retarget_incl(model, new, target, kom_deg)
        if target == "ELL1H":
            _to_h3_stigma(model, new)
    elif src in _ELL1_LIKE and target in _ECCENTRIC:
        if src == "ELL1H":
            _from_h3_stigma(model)
        eps1, eps2 = _f(model, "EPS1"), _f(model, "EPS2")
        (ecc, om), (se, so) = propagate(
            lambda e1, e2: (jnp.hypot(e1, e2), jnp.arctan2(e1, e2)),
            [eps1, eps2], [_u(model, "EPS1"), _u(model, "EPS2")],
        )
        om = float(om) % (2 * np.pi)
        frozen_e = (
            model.param_meta.get("EPS1", ParamValueMeta(spec=None)).frozen
            or model.param_meta.get("EPS2", ParamValueMeta(spec=None)).frozen
        )
        _set(model, new, "ECC", ecc, unc=se, frozen=frozen_e)
        _set(model, new, "OM", om, unc=so, frozen=frozen_e)
        t0 = _dd_shift(_epoch_dd(model, "TASC"), om / (2 * np.pi) * pb_s)
        _, (st,) = propagate(
            lambda ta, w, pb: ta + w / (2 * jnp.pi) * pb,
            [0.0, om, pb_s],
            [_u(model, "TASC"), so, _u(model, "PB")],
        )
        model.params["T0"] = t0
        model.param_meta["T0"] = ParamValueMeta(
            spec=new.specs["T0"],
            frozen=model.param_meta["TASC"].frozen,
            uncertainty=st,
        )
        _drop(model, "EPS1", "EPS2", "TASC", "EPS1DOT", "EPS2DOT", "LNEDOT")
        _retarget_incl(model, new, target, kom_deg)
    elif src == "ELL1H" and target in ("ELL1", "ELL1K"):
        _from_h3_stigma(model)
        _drop(model, "H3", "H4", "STIGMA", "NHARMS")
    elif src in ("ELL1", "ELL1K") and target == "ELL1H":
        _to_h3_stigma(model, new)
    elif src in _ECCENTRIC and target in _ECCENTRIC:
        _retarget_incl(model, new, target, kom_deg)
    elif src in _ELL1_LIKE and target in _ELL1_LIKE:
        pass
    else:
        raise NotImplementedError(f"conversion {src} -> {target}")

    model.meta["BINARY"] = target
    model.clear_caches()  # jitted programs captured the old component
    new.validate(model.params, model.meta)
    log.info(f"converted binary {old.model_name} -> {target}")
    return model


def _retarget_incl(model, new, target, kom_deg):
    """Map the inclination parameterization between eccentric flavors:
    SINI <-> SHAPMAX (DDS) <-> KIN/KOM (DDK)."""
    # the source's frozen state, captured BEFORE any _drop below
    frz = _was_free_incl(model)
    # normalize to SINI first
    sini = s_sini = None
    if "SHAPMAX" in model.params:
        (sini,), (s_sini,) = propagate(
            lambda s: 1.0 - jnp.exp(-s),
            [_f(model, "SHAPMAX")], [_u(model, "SHAPMAX")],
        )
        _drop(model, "SHAPMAX")
    elif "KIN" in model.params:
        (sini,), (s_sini,) = propagate(
            lambda k: jnp.sin(k), [_f(model, "KIN")], [_u(model, "KIN")],
        )
        _drop(model, "KIN", "KOM")
    elif "SINI" in model.params:
        sini, s_sini = _f(model, "SINI"), _u(model, "SINI")

    if sini is None:
        return
    if target == "DDS":
        (sm,), (ssm,) = propagate(
            lambda s: -jnp.log(1.0 - s), [sini], [s_sini],
        )
        _set(model, new, "SHAPMAX", sm, unc=ssm, frozen=frz)
        _drop(model, "SINI")
    elif target == "DDK":
        # convention caveat exactly as the reference warns: KIN and
        # 180 deg - KIN are equally consistent with SINI
        (kin,), (skin,) = propagate(
            lambda s: jnp.arcsin(s), [sini], [s_sini],
        )
        log.warning(
            "Using KIN = arcsin(SINI); 180 deg - KIN is an equally valid "
            "solution (reference binaryconvert.py caveat)"
        )
        _set(model, new, "KIN", kin, unc=skin, frozen=frz)
        _set(model, new, "KOM", np.deg2rad(kom_deg), frozen=True)
        _drop(model, "SINI")
    elif target == "BT":
        _drop(model, "SINI", "M2")
    else:  # DD keeps SINI (sini is non-None: the early return covers absence)
        if "SINI" not in model.params:
            _set(model, new, "SINI", sini, unc=s_sini, frozen=frz)


def _was_free_incl(model):
    for n in ("SINI", "SHAPMAX", "KIN"):
        pm = model.param_meta.get(n)
        if pm is not None:
            return pm.frozen
    return True


def _from_h3_stigma(model):
    """ELL1H orthometric (H3, STIGMA/H4) -> (M2, SINI) in place, with
    propagated sigmas (Freire & Wex 2010 eqs 20-22)."""
    h3 = _f(model, "H3")
    stig = _f(model, "STIGMA")
    if stig == 0.0 and "H4" in model.params and h3:
        stig = _f(model, "H4") / h3
    if not stig:
        _drop(model, "H3", "H4", "STIGMA", "NHARMS")
        return
    (sini, m2), (ss, sm) = propagate(
        lambda h, st: (2 * st / (1 + st**2), h / (TSUN_S * st**3)),
        [h3, stig], [_u(model, "H3"), _u(model, "STIGMA")],
    )
    model.params["SINI"] = float(sini)
    model.params["M2"] = float(m2)
    spec_src = next(c for c in model.components if isinstance(c, PulsarBinary))
    for n, v, s in (("SINI", sini, ss), ("M2", m2, sm)):
        spec = spec_src.specs.get(n)
        if spec is None:
            from pint_tpu.models.parameter import ParamSpec

            spec = ParamSpec(n, unit="")
        model.param_meta[n] = ParamValueMeta(
            spec=spec, frozen=model.param_meta.get("H3", ParamValueMeta(spec=None)).frozen,
            uncertainty=s,
        )
    _drop(model, "H3", "H4", "STIGMA", "NHARMS")


def _to_h3_stigma(model, new):
    """(M2, SINI) -> orthometric (H3, STIGMA) in place."""
    m2, sini = _f(model, "M2"), _f(model, "SINI")
    if m2 and sini:
        # the engine must evaluate the exact STIGMA form, not the
        # truncated 3-harmonic H3-only expansion (the builder keys this
        # off STIGMA presence; mirror it here)
        new.h_mode = "stigma"
        (h3, stig), (sh, sst) = propagate(
            lambda m, s: (
                TSUN_S * m * (s / (1 + jnp.sqrt(1 - s**2))) ** 3,
                s / (1 + jnp.sqrt(1 - s**2)),
            ),
            [m2, sini], [_u(model, "M2"), _u(model, "SINI")],
        )
        frz = (
            model.param_meta.get("M2", ParamValueMeta(spec=None)).frozen
            or model.param_meta.get("SINI", ParamValueMeta(spec=None)).frozen
        )
        _set(model, new, "H3", h3, unc=sh, frozen=frz)
        _set(model, new, "STIGMA", stig, unc=sst, frozen=frz)
    _drop(model, "M2", "SINI")
