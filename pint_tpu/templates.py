"""Photon pulse-profile templates: wrapped-Gaussian components + unbinned
maximum-likelihood fitting.

Reference: pint/templates/ (lcprimitives.py LCGaussian, lctemplate.py
LCTemplate, lcfitters.py LCFitter — ~4.8k LoC of profile machinery; this
module implements the load-bearing core: the 'gauss' text format the
reference ships (e.g. tests/datafile/templateJ0030.3gauss), template
evaluation as a wrapped-Gaussian mixture, and the unbinned weighted
log-likelihood fit of a phase offset / component parameters used by
photonphase-style analyses).

Template density over phase x in [0,1):
    f(x) = norm_free + sum_i ampl_i * N_w(x; phas_i, fwhm_i)
with N_w a Gaussian wrapped over +-N cycles and the constant chosen so
f integrates to 1 (amplitudes are the components' integral fractions).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

FWHM_TO_SIGMA = 1.0 / (2.0 * np.sqrt(2.0 * np.log(2.0)))
_WRAPS = 3


@dataclass
class LCGaussian:
    phase: float
    fwhm: float
    ampl: float

    def density(self, x: np.ndarray) -> np.ndarray:
        """Wrapped normalized Gaussian at phases x (cycles)."""
        s = self.fwhm * FWHM_TO_SIGMA
        out = np.zeros_like(x, dtype=float)
        for k in range(-_WRAPS, _WRAPS + 1):
            out += np.exp(-0.5 * ((x - self.phase + k) / s) ** 2)
        return out / (s * np.sqrt(2 * np.pi))


@dataclass
class LCTemplate:
    components: list[LCGaussian] = field(default_factory=list)

    @property
    def total_ampl(self) -> float:
        return sum(c.ampl for c in self.components)

    def __call__(self, phases: np.ndarray) -> np.ndarray:
        """Normalized profile density at phases (cycles)."""
        x = np.mod(np.asarray(phases, float), 1.0)
        out = np.full_like(x, max(1.0 - self.total_ampl, 0.0))
        for c in self.components:
            out = out + c.ampl * c.density(x)
        return out

    def shifted(self, dphi: float) -> "LCTemplate":
        return LCTemplate(
            [LCGaussian((c.phase + dphi) % 1.0, c.fwhm, c.ampl) for c in self.components]
        )

    # --- 'gauss' text format (reference lctemplate.prim_io) --------------------

    @classmethod
    def read(cls, path: str) -> "LCTemplate":
        vals: dict[str, float] = {}
        with open(path) as f:
            for line in f:
                m = re.match(r"\s*(\w+)\s*=\s*([-\d.eE+]+)", line)
                if m:
                    vals[m.group(1)] = float(m.group(2))
        comps = []
        k = 1
        while f"phas{k}" in vals:
            comps.append(
                LCGaussian(vals[f"phas{k}"], vals[f"fwhm{k}"], vals[f"ampl{k}"])
            )
            k += 1
        if not comps:
            raise ValueError(f"{path}: no gaussian components found")
        return cls(comps)

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write("# gauss\n" + "-" * 25 + "\n")
            f.write("const = 0.00000 +/- 0.00000\n")
            for k, c in enumerate(self.components, start=1):
                f.write(f"phas{k} = {c.phase:.5f} +/- 0.00000\n")
                f.write(f"fwhm{k} = {c.fwhm:.5f} +/- 0.00000\n")
                f.write(f"ampl{k} = {c.ampl:.5f} +/- 0.00000\n")
            f.write("-" * 25 + "\n")


def lnlikelihood(template: LCTemplate, phases, weights=None, dphi: float = 0.0) -> float:
    """Unbinned weighted photon log-likelihood (reference lcfitters.py):
    sum log(w f(phi - dphi) + (1 - w))."""
    f = template(np.asarray(phases) - dphi)
    if weights is None:
        return float(np.sum(np.log(np.maximum(f, 1e-300))))
    w = np.asarray(weights)
    return float(np.sum(np.log(np.maximum(w * f + (1.0 - w), 1e-300))))


def fit_phase_shift(template: LCTemplate, phases, weights=None, n_grid: int = 256):
    """Maximum-likelihood phase offset of the data vs the template, with a
    Fisher-information uncertainty (reference lcfitters.fit_position)."""
    grid = np.linspace(0, 1, n_grid, endpoint=False)
    ll = np.array([lnlikelihood(template, phases, weights, d) for d in grid])
    i = int(np.argmax(ll))
    # parabolic refinement around the grid peak
    lm, l0, lp = ll[(i - 1) % n_grid], ll[i], ll[(i + 1) % n_grid]
    denom = lm - 2 * l0 + lp
    frac = 0.5 * (lm - lp) / denom if denom != 0 else 0.0
    dphi = (grid[i] + frac / n_grid) % 1.0
    curv = -denom * n_grid**2  # d2(-ll)/dphi2
    err = 1.0 / np.sqrt(curv) if curv > 0 else np.nan
    return dphi, err, float(l0)
