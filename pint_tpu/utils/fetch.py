"""Resilient file acquisition: retries, backoff, mirrors, quarantine.

The clock-corrections repository sync (astro/global_clock.py) grew its
download logic ad hoc: one attempt per mirror, no timeout policy, no
validation — a corrupt download poisoned the cache until expiry. This
module is the one shared primitive every remote acquisition goes through
(global_clock, and any future EOP/ephemeris mirror sync):

- **Bounded retries with exponential backoff + jitter.** Each mirror is
  tried once per round, rounds repeat up to ``PINT_TPU_FETCH_ATTEMPTS``
  times (default 3) with ``PINT_TPU_FETCH_BACKOFF``-seconds base delay
  doubling between rounds (±10% jitter so a fleet of workers doesn't
  retry in lockstep). Tests monkeypatch :data:`_sleep` to unit-lock the
  schedule without real waiting.
- **Per-attempt timeouts** (``PINT_TPU_FETCH_TIMEOUT``, default 30 s)
  on http(s) mirrors.
- **Atomic writes**: the payload lands in a pid-suffixed temp file and
  is renamed over the destination only after validation, so a killed
  process or corrupt download never leaves a half-written cache entry.
- **Post-download validation + quarantine**: payloads must be non-empty
  and pass the caller's ``validate`` hook (parseability); a failing
  payload is moved to a ``quarantine/`` sibling of the destination —
  preserved for diagnosis, never served from the cache — the attempt
  counts as failed, and the retry loop continues.
- **Degradation ledger wiring** (ops/degrade.py): a quarantined payload
  records ``fetch.corrupt_quarantined``; exhausting every mirror records
  ``fetch.mirror_failed`` before :class:`FetchError` raises, so under
  ``PINT_TPU_DEGRADED=error`` a production pipeline refuses instead of
  silently falling back to whatever is cached.
- **Fault injection** (pint_tpu/testing/faults.py): the ``fetch`` /
  ``fetch.payload`` sites let tier-1 drive refusals, timeouts, and
  corrupt payloads deterministically with no network.

Mirrors may be http(s) URLs, ``file://`` URLs, or plain directories.
"""

from __future__ import annotations

import os
import random
import time
from pathlib import Path

from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.fetch")

__all__ = ["FetchError", "fetch"]

#: injectable sleep so tests lock the backoff schedule without waiting
_sleep = time.sleep


class FetchError(OSError):
    """Every mirror failed for every attempt round."""

    def __init__(self, msg: str, attempts: int = 0,
                 last_error: Exception | None = None):
        super().__init__(msg)
        self.attempts = attempts
        self.last_error = last_error


def _read_mirror(base: str, name: str, timeout_s: float) -> bytes:
    """One download attempt of `name` from the mirror at `base`."""
    from pint_tpu.testing import faults

    faults.maybe_raise("fetch", f"{base}/{name}")
    if base.startswith(("http://", "https://")):
        from urllib.request import urlopen

        url = base.rstrip("/") + "/" + name
        with urlopen(url, timeout=timeout_s) as r:
            data = r.read()
    else:
        if base.startswith("file://"):
            base = base[len("file://"):]
        src = Path(base) / name
        if not src.exists():
            raise FileNotFoundError(f"{name} not in repository {base}")
        data = src.read_bytes()
    return faults.mangle("fetch.payload", data, f"{base}/{name}")


def _quarantine(dest: Path, data: bytes, reason: str) -> None:
    """Preserve a failed payload beside the cache, never in it."""
    from pint_tpu.ops import degrade

    qdir = dest.parent / "quarantine"
    qdir.mkdir(parents=True, exist_ok=True)
    qpath = qdir / dest.name
    qpath.write_bytes(data)
    degrade.record(
        "fetch.corrupt_quarantined", dest.name,
        f"downloaded payload failed validation ({reason}); preserved at "
        f"{qpath}, cache untouched",
        fix="inspect the quarantined file and the mirror serving it",
    )


def fetch(name: str, dest: Path, mirrors: list[str],
          validate=None,
          attempts: int | None = None,
          backoff_s: float | None = None,
          timeout_s: float | None = None) -> Path:
    """Download `name` from the first healthy mirror into `dest`.

    `validate(payload: bytes)` may raise (or return False) to reject a
    corrupt payload — rejected payloads are quarantined and the attempt
    retried. Raises :class:`FetchError` after every mirror has failed
    `attempts` rounds; callers with a stale local copy catch it and
    record their own degradation (e.g. ``clock.stale_cache``).
    """
    from pint_tpu.ops import degrade
    from pint_tpu.utils import knobs

    if not mirrors:
        raise ValueError("fetch needs at least one mirror")
    if attempts is None:
        attempts = int(knobs.get("PINT_TPU_FETCH_ATTEMPTS") or 3)
    if backoff_s is None:
        backoff_s = float(knobs.get("PINT_TPU_FETCH_BACKOFF") or 0.5)
    if timeout_s is None:
        timeout_s = float(knobs.get("PINT_TPU_FETCH_TIMEOUT") or 30.0)

    dest = Path(dest)
    last_err: Exception | None = None
    n_tried = 0
    for round_no in range(max(attempts, 1)):
        if round_no:
            # exponential backoff between rounds, jittered so a worker
            # fleet retrying the same dead mirror doesn't sync up
            _sleep(backoff_s * (2.0 ** (round_no - 1))
                   * (1.0 + 0.1 * random.random()))
        for base in mirrors:  # mirror rotation within each round
            n_tried += 1
            try:
                data = _read_mirror(base, name, timeout_s)
            except Exception as e:  # jaxlint: disable=silent-except — bounded retry; exhaustion is recorded below
                last_err = e
                log.info(f"fetch {name} from {base} failed "
                         f"(attempt {n_tried}): {e}")
                continue
            reason = None
            if not data:
                reason = "empty payload"
            elif validate is not None:
                try:
                    if validate(data) is False:
                        reason = "validator returned False"
                except Exception as e:  # jaxlint: disable=silent-except — rejection is quarantined+recorded below
                    reason = f"validator raised {type(e).__name__}: {e}"
            if reason is not None:
                _quarantine(dest, data, reason)
                last_err = ValueError(f"{name}: {reason}")
                continue
            dest.parent.mkdir(parents=True, exist_ok=True)
            tmp = dest.with_suffix(dest.suffix + f".tmp{os.getpid()}")
            tmp.write_bytes(data)
            tmp.replace(dest)
            return dest
    degrade.record(
        "fetch.mirror_failed", name,
        f"every mirror failed after {n_tried} attempts "
        f"({len(mirrors)} mirror(s) x {attempts} round(s)); last: {last_err}",
        fix="check the mirror list / network, or pre-populate the cache",
    )
    raise FetchError(
        f"{name}: all mirrors failed after {n_tried} attempts ({last_err})",
        attempts=n_tried, last_error=last_err,
    )
