"""The sanctioned environment-knob registry.

Every ``PINT_TPU_*`` behavior toggle is read through this module, for two
reasons the analysis layer (pint_tpu/analysis/) enforces mechanically:

- **One inventory.** The KNOBS table below is the complete, documented
  list of environment switches the package honors; a knob that is not
  registered here does not exist (``get``/``flag`` raise ``KeyError``),
  so stale call sites and typo'd names fail loudly instead of silently
  reading an empty default forever.
- **Lintable call sites.** ``python -m pint_tpu.analysis.lint`` flags any
  raw ``os.environ`` / ``os.getenv`` read in ``pint_tpu/`` outside this
  module (rule ``env-read``): scattered raw reads are how knobs drift out
  of the docs and out of cache keys. Genuinely dynamic reads (e.g. the
  TEMPO/TEMPO2 clock-dir convention, jax distributed autodetect markers)
  carry an inline ``# jaxlint: disable=env-read`` with a justification.

The registry stores only (default, doc); values are ALWAYS re-read from
``os.environ`` so tests can monkeypatch knobs mid-process.
"""

from __future__ import annotations

import os

__all__ = ["KNOBS", "get", "flag", "describe"]

#: name -> (default, one-line doc). The default is what ``get`` returns
#: when the variable is unset (None = no default).
KNOBS: dict[str, tuple[str | None, str]] = {
    # --- fit path / compile machinery ------------------------------------------
    "PINT_TPU_PERF": ("0", "1: every fit collects a stage breakdown onto FitResult.perf"),
    "PINT_TPU_FUSED_FIT": ("0", "1: downhill fitters default to the fused on-device LM loop"),
    "PINT_TPU_HOST_SOLVE": ("0", "1: force the fitters' dense solves onto the host (CPU test mode)"),
    "PINT_TPU_CPU_FUSION_WORKAROUND": ("0", "1: re-enable the per-program XLA:CPU fusion-pass disable"),
    "PINT_TPU_COMPILE_CACHE": (None, "legacy knob: persistent-cache dir override, 0 disables"),
    "PINT_TPU_XLA_CACHE": ("1", "0: disable the persistent XLA compilation cache"),
    "PINT_TPU_XLA_CACHE_DIR": (None, "persistent XLA cache directory override"),
    "PINT_TPU_AOT_EXPORT": ("0", "1: AOT-eligible programs round-trip their compiled executables through the on-disk artifact store (zero-trace warm starts; pint_tpu warmup populates it)"),
    "PINT_TPU_AOT_CACHE_KEEP": ("128", "serialized-executable artifacts kept (oldest pruned)"),
    "PINT_TPU_EXPECT_WARM": ("0", "1: retrace-zero contract — any TimedProgram trace/compile escalates to a strict audit failure (implies AOT deserialization)"),
    # --- program audit (pint_tpu/analysis/) ------------------------------------
    "PINT_TPU_AUDIT": ("warn", "jaxpr auditor mode: warn (default), strict (raise), 0 (off)"),
    "PINT_TPU_AUDIT_CONST_BYTES": ("262144", "large-constant-capture audit threshold in bytes"),
    "PINT_TPU_DDFLOW": ("1", "0: skip the dd-flow precision-dataflow audit passes (analysis/ddflow.py)"),
    "PINT_TPU_COST_BUDGET_TOL": ("0.15", "fractional static-cost growth tolerated by python -m pint_tpu.analysis.cost --check"),
    # --- ephemeris / astrometry chain ------------------------------------------
    "PINT_TPU_EPHEM": (None, "path to a JPL SPK kernel; unset = analytic ephemeris"),
    "PINT_TPU_KERNEL_EPHEM": ("auto", "Chebyshev kernel-pack serving: auto (pack a configured SPK kernel), 1 (also snapshot the analytic/N-body path), 0 (off)"),
    "PINT_TPU_KERNEL_EPHEM_CACHE": ("1", "0: disable the kernel-pack disk cache (packs rebuild per process)"),
    "PINT_TPU_KERNEL_EPHEM_KEEP": ("8", "kernel-pack cache entries kept (oldest pruned)"),
    "PINT_TPU_NBODY": ("1", "0: disable the N-body ephemeris refinement"),
    "PINT_TPU_NBODY_CACHE": ("1", "0: disable the N-body solution disk cache"),
    "PINT_TPU_NBODY_COMB": ("0", "1: add the comb anchor periods to the N-body band design"),
    "PINT_TPU_EOP": (None, "path to an IERS finals2000A file; unset = zero EOP"),
    "PINT_TPU_REPREPARE_REUSE_US": ("10", "re-preparation geometry-reuse threshold in us (0 disables the fast path)"),
    # --- prepare path (toas.py, astro/device_prepare.py) -----------------------
    "PINT_TPU_DEVICE_PREPARE": ("auto", "TOA-prepare series on device: auto (non-CPU backends), 1 (force), 0 (host numpy)"),
    "PINT_TPU_PREPARE_CACHE": ("1", "0: disable the content-hash prepared-TOA disk cache"),
    "PINT_TPU_PREPARE_CACHE_KEEP": ("32", "prepared-TOA cache entries kept (oldest pruned)"),
    # --- fitter state / warm start (fitting/state.py) --------------------------
    "PINT_TPU_WARM_START": ("0", "1: downhill fits warm-start from / save a disk snapshot of the prior fit"),
    # --- incremental refits / timing sessions (fitting/incremental.py, serve/) --
    "PINT_TPU_INCR_MAX_FRAC": ("0.05", "appended-row fraction past which an incremental refit falls back to the full warm refit"),
    "PINT_TPU_INCR_MAX_SHIFT": ("3.0", "blocks-solve step bound in units of parameter sigma past which the incremental linearization is declared stale"),
    # --- serving engine (pint_tpu/serve/) --------------------------------------
    "PINT_TPU_SERVE_MAX_WAIT_MS": ("50", "continuous-batching lane deadline: max ms a queued request waits for its bucket to fill before dispatch"),
    "PINT_TPU_SERVE_QUEUE_DEPTH": ("256", "bounded serving queue: requests admitted beyond this depth are shed (serve.shed)"),
    "PINT_TPU_SERVE_POOL_SESSIONS": ("64", "warm session-pool capacity: LRU sessions beyond it are checkpointed + evicted (serve.evict)"),
    "PINT_TPU_SERVE_SHED_POLICY": ("reject", "overload policy: reject (refuse the new request) or drop_oldest (shed the oldest queued request instead)"),
    "PINT_TPU_SERVE_TENANT_RPS": ("0", "per-tenant token-bucket admission rate in requests/s (0: unlimited)"),
    "PINT_TPU_SERVE_DEADLINE_MS": ("0", "default per-request serving deadline in ms: queued past it, the request is shed (serve.deadline); 0 disables"),
    "PINT_TPU_SERVE_RETRIES": ("2", "bounded retries (with exponential backoff) for a transiently failed serving dispatch before the error is delivered"),
    "PINT_TPU_SERVE_RETRY_BACKOFF_MS": ("10", "base backoff in ms between serving dispatch retries (doubles per attempt)"),
    "PINT_TPU_SERVE_QUARANTINE_FAILS": ("3", "consecutive failed dispatches after which a serving lane's session is quarantined (serve.quarantine)"),
    "PINT_TPU_SERVE_WATCHDOG_S": ("30", "serving watchdog threshold in s: a dispatch hung past it is abandoned, its session quarantined, the worker replaced; 0 disables"),
    "PINT_TPU_SERVE_JOURNAL_FSYNC": ("8", "write-ahead journal fsync batching: fsync every N records (1: every record, 0: only at rotation/close); records always flush to the OS before the ticket acks"),
    # --- replicated serving fleet (serve/gateway.py, serve/fleet.py) -----------
    "PINT_TPU_GATEWAY_PORT": ("0", "serve the HTTP gateway (submit/ticket/metrics, localhost) on this port; 0 = an ephemeral port chosen at bind"),
    "PINT_TPU_FLEET_REPLICAS": ("2", "replica worker processes a ReplicaFleet spawns by default"),
    "PINT_TPU_FLEET_READY_TIMEOUT_S": ("600", "replica READY:: handshake budget in s: a worker not ready past it (hung OR dead) is reaped and spawn_all starts the fleet degraded at R-1 (serve.replica_lost)"),
    "PINT_TPU_MIGRATE_TIMEOUT_S": ("30", "live session migration budget in s: a checkpoint-handoff (export + import + journal replay) past it fails the migration instead of stalling the fleet"),
    # --- durable campaigns (pint_tpu/campaign/) --------------------------------
    "PINT_TPU_CAMPAIGN_CHECKPOINT_EVERY": ("1", "campaign progress-snapshot cadence in completed units (campaign/runner.py); unit RESULTS are always durable per unit"),
    "PINT_TPU_CAMPAIGN_KEEP": ("2", "campaign snapshot generations kept (>= 2, so a kill mid-write always leaves an intact previous generation)"),
    # --- observability (pint_tpu/obs/) -----------------------------------------
    "PINT_TPU_TRACE": ("0", "request tracing: 0 off (zero-cost), 1 on (spans as JSON Lines under <cache_root>/traces), any other value = the output directory"),
    "PINT_TPU_METRICS_PORT": ("0", "serve the OpenMetrics endpoint (/metrics + /healthz, localhost) on this port when the engine starts; 0 disables"),
    "PINT_TPU_FLIGHT_EVENTS": ("512", "flight-recorder ring size: recent structured events kept for crash reports; 0 disables"),
    # --- Bayesian noise engine (fitting/noise_like.py, sampler.py) -------------
    "PINT_TPU_NOISE_CHAINS": ("4", "vmapped noise-posterior chains per sample() call"),
    "PINT_TPU_NOISE_RESTARTS": ("8", "batched optimizer restarts for ML noise estimation"),
    "PINT_TPU_NUTS_WARMUP": ("0", "HMC dual-averaging warmup steps (0: half the chain length)"),
    "PINT_TPU_NUTS_TARGET_ACCEPT": ("0.8", "dual-averaging target acceptance for the HMC kernel"),
    "PINT_TPU_NUTS_MAX_LEAPFROG": ("16", "leapfrog steps per HMC trajectory"),
    "PINT_TPU_OBS_JSON": ("", "colon-separated extra observatories.json overlays"),
    # --- clocks ----------------------------------------------------------------
    "PINT_TPU_CLOCK_REPO": (None, "clock-corrections repository (https/file URL or directory)"),
    "PINT_CLOCK_OVERRIDE": (None, "directory searched first for clock files"),
    # --- robustness layer (ops/degrade.py, utils/fetch.py, testing/faults.py) --
    "PINT_TPU_DEGRADED": ("warn", "degradation ledger escalation: warn (default), error (raise), 0 (silent record)"),
    "PINT_TPU_FAULTS": ("", "fault-injection spec site:mode[*N][,...] (pint_tpu/testing/faults.py)"),
    "PINT_TPU_FETCH_ATTEMPTS": ("3", "download retry rounds per mirror (utils/fetch.py)"),
    "PINT_TPU_FETCH_BACKOFF": ("0.5", "base seconds between download retry rounds (doubles per round)"),
    "PINT_TPU_FETCH_TIMEOUT": ("30", "per-attempt download timeout in seconds"),
    # --- caches ----------------------------------------------------------------
    "PINT_TPU_CACHE_DIR": (None, "disk-cache root (default ~/.cache/pint_tpu)"),
}


def get(name: str, default: str | None = "__registered__") -> str | None:
    """The knob's current value (env, falling back to the registered
    default). Unregistered names raise ``KeyError`` — register new knobs
    in ``KNOBS`` so they stay documented and lintable."""
    if name not in KNOBS:
        raise KeyError(
            f"{name} is not a registered pint_tpu knob; add it to "
            "pint_tpu.utils.knobs.KNOBS"
        )
    if default == "__registered__":
        default = KNOBS[name][0]
    return os.environ.get(name, default)  # jaxlint: disable=env-read — the registry itself


def flag(name: str) -> bool:
    """Boolean knob with the package-wide convention: the string "1" is
    true, anything else (including unset with a "0" default) is false."""
    return get(name) == "1"


def describe() -> str:
    """Human-readable knob inventory (docs / --help surfaces)."""
    width = max(len(n) for n in KNOBS)
    lines = []
    for n, (default, doc) in sorted(KNOBS.items()):
        d = "unset" if default is None else repr(default)
        lines.append(f"{n:<{width}s}  [{d}] {doc}")
    return "\n".join(lines)
