"""Shared disk-cache helpers.

All pint_tpu disk caches live under ``$PINT_TPU_CACHE_DIR`` (default
``~/.cache/pint_tpu``): prepared TOAs (toas.py), the N-body ephemeris
solution (astro/nbody.py), synced clock corrections (astro/global_clock.py),
the persistent XLA compilation cache, and benchmark datasets (bench.py).
"""

from __future__ import annotations

import hashlib
import os
from pathlib import Path

_FINGERPRINT: str | None = None


def cache_root() -> Path:
    from pint_tpu.utils import knobs

    return Path(
        knobs.get("PINT_TPU_CACHE_DIR")
        or os.path.expanduser("~/.cache/pint_tpu")
    )


def source_fingerprint() -> str:
    """Hash of every pint_tpu source file — a conservative cache key
    component: ANY source change invalidates entries keyed on it.
    Computed once per process (~15k LoC, a few ms)."""
    global _FINGERPRINT
    if _FINGERPRINT is None:
        import pint_tpu

        pkg = Path(pint_tpu.__file__).parent
        h = hashlib.sha256()
        for p in sorted(pkg.rglob("*.py")):
            h.update(p.read_bytes())
        _FINGERPRINT = h.hexdigest()[:16]
    return _FINGERPRINT
