"""Lightweight structured logging for pint_tpu.

The reference wraps loguru with a dedup filter (pint/logging.py:125-236);
loguru is not a dependency here, so we provide the same surface (setup(),
per-module loggers, repeated-message suppression) on stdlib logging.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"
_configured = False


# dedup state survives handler re-initialization: repeated setup() calls
# (multi-device dryruns re-point the backend and re-init logging; notebook
# reloads) must NOT reset the suppression counts, or every re-init earns
# the chatty messages another max_repeats round
_dedup_counts: dict[str, int] = {}
# how many records were actually DROPPED (per dedup key / log_once key):
# also process-global — a handler re-init used to make these counts
# unreachable (they lived implicitly in _dedup_counts arithmetic tied to
# a filter instance's max_repeats); now every suppression is counted
# here and exported as the `log_suppressed` metrics-registry counter
# (pint_tpu/obs/metrics.py)
_suppressed_counts: dict[str, int] = {}


class DedupFilter(logging.Filter):
    """Suppress exact-duplicate log records after the first N occurrences.

    Mirrors the behavior of the reference's LogFilter (pint/logging.py:125):
    chatty per-TOA warnings collapse to a single line. The counts are
    process-global (shared by every filter instance), so a re-created
    handler keeps suppressing what the old one suppressed — and the
    suppression tally itself survives re-init and is visible through
    :func:`suppressed_total`.
    """

    def __init__(self, max_repeats: int = 3):
        super().__init__()
        self.max_repeats = max_repeats
        self._counts = _dedup_counts

    def filter(self, record: logging.LogRecord) -> bool:  # noqa: A003
        key = f"{record.name}:{record.levelno}:{record.getMessage()}"
        n = self._counts.get(key, 0)
        self._counts[key] = n + 1
        if n == self.max_repeats:
            record.msg = f"{record.msg} [further repeats suppressed]"
        if n > self.max_repeats:
            _suppressed_counts[key] = _suppressed_counts.get(key, 0) + 1
        return n <= self.max_repeats


def suppressed_total() -> int:
    """Log records dropped by the dedup filter + :func:`log_once`
    repeats, process-wide — survives any number of handler re-inits
    (``setup()`` calls) because the tally never lives on a filter
    instance. Exported as the ``log_suppressed`` registry counter."""
    return sum(_suppressed_counts.values())


def suppressed_counts() -> dict[str, int]:
    """Per-message suppression tallies (diagnostics surface)."""
    return dict(_suppressed_counts)


def setup(level: str = "INFO", sink=sys.stderr, dedup: bool = True) -> None:
    """Configure the root pint_tpu logger (reference: pint.logging.setup)."""
    global _configured
    root = logging.getLogger("pint_tpu")
    root.handlers.clear()
    handler = logging.StreamHandler(sink)
    handler.setFormatter(logging.Formatter(_FORMAT))
    if dedup:
        handler.addFilter(DedupFilter())
    root.addHandler(handler)
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    _configured = True


def get_level(starting: str = "WARNING", verbosity: int = 0, quietness: int = 0) -> str:
    """-v/-q CLI arithmetic (reference: pint/logging.py:323)."""
    levels = ["TRACE", "DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"]
    aliases = {"TRACE": "DEBUG"}  # stdlib has no TRACE
    idx = levels.index(starting.upper()) - verbosity + quietness
    idx = min(max(idx, 0), len(levels) - 1)
    name = levels[idx]
    return aliases.get(name, name)


def get_logger(name: str) -> logging.Logger:
    if not _configured:
        setup()
    return logging.getLogger(name)


_once_keys: set[str] = set()


def log_once(logger: logging.Logger, msg: str, level: int = logging.INFO) -> None:
    """Emit `msg` at most once per process (keyed on logger+level+message).

    Tighter than the DedupFilter (which allows max_repeats before
    latching): routine per-preparation summaries — "prepared TOAs",
    observatory loads — repeat identically every time the same data set
    is re-prepared (zero_residuals passes, per-shard re-init in the
    multichip dryrun), and one line carries all the information."""
    key = f"{logger.name}:{level}:{msg}"
    if key in _once_keys:
        _suppressed_counts[f"once:{key}"] = \
            _suppressed_counts.get(f"once:{key}", 0) + 1
        return
    _once_keys.add(key)
    logger.log(level, msg)
