"""Provenance stamping for generated outputs.

The reference stamps everything it writes (par, tim, polyco) with an
info block — version, invoking command, creation date (reference
utils.py:1585 ``info_string``) — so a file found on disk two years later
identifies the toolchain that produced it. This module is the pint_tpu
equivalent: one header format, one implementation, used by
``TimingModel.as_parfile`` (models/builder.py), ``io/tim.py write_tim``
and ``polycos.Polycos.write``.

The headers are comment lines in each format's own comment convention,
so every parser in ``pint_tpu/io`` (and the reference toolchains) skips
them: round-tripping a stamped file is lossless (locked by
tests/test_io.py / tests/test_polycos_golden.py).
"""

from __future__ import annotations

import sys
from datetime import datetime, timezone

__all__ = ["provenance_lines", "provenance_header"]


def provenance_lines(fmt: str) -> list[str]:
    """The provenance fields, without comment markers:
    created-date (UTC), package version, invoking command, format tag."""
    from pint_tpu import __version__

    cmd = " ".join(sys.argv) if sys.argv and sys.argv[0] else "(interactive)"
    return [
        f"Created: {datetime.now(timezone.utc).strftime('%Y-%m-%dT%H:%M:%S+00:00')}",
        f"pint_tpu_version: {__version__}",
        f"Command: {cmd}",
        f"Format: {fmt}",
    ]


def provenance_header(fmt: str, comment: str = "# ") -> str:
    """The stamped header block, each line prefixed with the target
    format's comment convention (``# `` for par/polyco, ``C `` for
    Tempo2 tim files), newline-terminated."""
    return "".join(f"{comment}{line}\n" for line in provenance_lines(fmt))
