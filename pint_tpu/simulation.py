"""Fake-TOA simulation: uniform grids, zero-residual iteration, noise draws.

Reference: pint/simulation.py (zero_residuals:49 — iteratively shift TOA
times until the model's residuals vanish, so fakes sit exactly on the model;
make_fake_toas_uniform:191; make_fake_toas_fromtim). This is also the test
suite's "fake backend" (SURVEY.md §4.4): fitters must recover injected
parameters from data generated here.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.astro import time as ptime
from pint_tpu.astro.observatories import get_observatory
from pint_tpu.residuals import Residuals
from pint_tpu.toas import TOAs, prepare_arrays
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.simulation")


def zero_residuals(
    toas: TOAs,
    model,
    maxiter: int = 10,
    tolerance_s: float = 1e-9,
) -> TOAs:
    """Shift TOA (UTC) times until model residuals are < tolerance.

    Each pass recomputes the full clock/TDB/posvel pipeline at the shifted
    times, exactly like the reference (simulation.py:49-95, whose default
    tolerance is likewise 1 ns). If the iteration stalls within 10x the
    tolerance the best-effort result is returned with a warning — fakes a
    few ns off the model are still far below any TOA uncertainty — and only
    a genuinely diverged iteration raises.
    """
    cur = toas
    best, best_worst = toas, np.inf
    for i in range(maxiter):
        r = Residuals(cur, model, subtract_mean=False, track_mode="nearest").time_resids
        worst = float(np.max(np.abs(r)))
        if worst < tolerance_s:
            log.info(f"zero_residuals converged after {i} passes (worst {worst:.2e} s)")
            return cur
        if worst < best_worst:
            best, best_worst = cur, worst
        cur = _reprepare(cur, -r)
    if best_worst < 10.0 * tolerance_s:
        log.warning(
            f"zero_residuals stalled at {best_worst:.2e} s after {maxiter} passes "
            f"(tolerance {tolerance_s} s); returning best-effort TOAs"
        )
        return best
    raise RuntimeError(
        f"zero_residuals did not reach {tolerance_s} s in {maxiter} passes (worst {best_worst:.2e} s)"
    )


def _reprepare(toas: TOAs, shift_s: np.ndarray, force_full: bool = False) -> TOAs:
    """Re-run the preparation pipeline with the RAW site UTC shifted by
    shift_s, preserving the clock-chain settings (never re-applies the clock
    corrections already folded into toas.utc).

    **Geometry reuse fast path.** For sub-threshold shifts (default 10 us,
    ``PINT_TPU_REPREPARE_REUSE_US``) the already-prepared clock
    corrections, EOP, site posvels and ephemeris columns are REUSED and
    only the time columns shift: the geometry error of evaluating those
    columns at a time dt away is bounded by (v_earth/c) * dt <= 1e-4 * dt
    — ~1 ns at the 10 us threshold, far below any TOA uncertainty, and
    the shifted TOAs stay exactly self-consistent for residual evaluation
    (the tensor's times and geometry come from the same object). The
    staleness ACCUMULATES across chained fast-path calls
    (``TOAs.geom_stale_s``); once the running total would cross the
    threshold the full pipeline runs and resets it, so the bound holds no
    matter how many noise realizations or zero-residual passes chain.
    This is what makes per-realization fake-TOA fleets
    (monte_carlo_uncertainty) and the late zero_residuals passes cost
    microseconds instead of a full clock/ephemeris rebuild each.
    """
    from pint_tpu.utils import knobs

    shift = np.asarray(shift_s, float)
    worst = float(np.max(np.abs(shift))) if shift.size else 0.0
    limit = float(knobs.get("PINT_TPU_REPREPARE_REUSE_US")) * 1e-6
    stale = getattr(toas, "geom_stale_s", 0.0) + worst
    if not force_full and stale <= limit:
        from dataclasses import replace

        return replace(
            toas,
            utc=toas.utc.add_seconds(shift),
            tdb=toas.tdb.add_seconds(shift),
            utc_raw=(None if toas.utc_raw is None
                     else toas.utc_raw.add_seconds(shift)),
            geom_stale_s=stale,
        )
    base = toas.utc_raw if toas.utc_raw is not None else toas.utc
    return prepare_arrays(
        base.add_seconds(shift_s),
        toas.error_us,
        toas.freq_mhz,
        toas.obs,
        flags=toas.flags,
        ephem=toas.ephem,
        planets=toas.planets,
        include_gps=toas.include_gps,
        include_bipm=toas.include_bipm,
        bipm_version=toas.bipm_version,
    )


def make_fake_toas_fromMJDs(
    mjds: np.ndarray,
    model,
    obs: str = "gbt",
    freq_mhz: float | np.ndarray = 1400.0,
    error_us: float | np.ndarray = 1.0,
    flags: list[dict] | None = None,
    add_noise: bool = False,
    add_correlated_noise: bool = False,
    rng: np.random.Generator | None = None,
    planets: bool | None = None,
) -> TOAs:
    """Fake TOAs at arbitrary MJDs lying exactly on `model`.

    `flags` (per-TOA dicts, e.g. ``{"f": "Rcvr1_2_GUPPI"}``) bind the model's
    mask parameters — EFAC/EQUAD/ECORR selections, JUMPs — exactly as real
    tim-file flags would. `add_noise` draws white noise scaled by the TOA
    errors; `add_correlated_noise` draws from the model's FULL noise
    covariance instead (reference make_fake_toas_fromMJDs simulation.py:240
    + add_correlated_noise:273)."""
    ntoas = len(mjds)
    utc = ptime.MJDEpoch.from_mjd_float(np.asarray(mjds, float))
    err = np.broadcast_to(np.asarray(error_us, float), (ntoas,)).copy()
    frq = np.broadcast_to(np.asarray(freq_mhz, float), (ntoas,)).copy()
    obs_name = get_observatory(obs).name
    obs_arr = np.array([obs_name] * ntoas)
    if planets is None:
        planets = bool(model.planet_shapiro)
    toas = prepare_arrays(
        utc, err, frq, obs_arr, flags=flags,
        ephem=model.ephem or "auto", planets=planets,
    )
    toas = zero_residuals(toas, model)
    if add_correlated_noise:
        toas = add_noise_from_model(toas, model, rng=rng)
    elif add_noise:
        rng = rng or np.random.default_rng()
        toas = _reprepare(toas, rng.standard_normal(ntoas) * err * 1e-6)
    return toas


def make_fake_toas_uniform(
    start_mjd: float,
    end_mjd: float,
    ntoas: int,
    model,
    obs: str = "gbt",
    freq_mhz: float | np.ndarray = 1400.0,
    error_us: float | np.ndarray = 1.0,
    flags: list[dict] | None = None,
    add_noise: bool = False,
    add_correlated_noise: bool = False,
    rng: np.random.Generator | None = None,
    planets: bool | None = None,
) -> TOAs:
    """Evenly spaced fake TOAs lying exactly on `model` (+ optional noise
    draw). Reference make_fake_toas_uniform, simulation.py:191."""
    return make_fake_toas_fromMJDs(
        np.linspace(start_mjd, end_mjd, ntoas), model, obs=obs,
        freq_mhz=freq_mhz, error_us=error_us, flags=flags,
        add_noise=add_noise, add_correlated_noise=add_correlated_noise,
        rng=rng, planets=planets,
    )


def add_noise_from_model(toas: TOAs, model, rng=None,
                         include_common: bool = True) -> TOAs:
    """Shift TOAs by one realization of the model's full noise covariance
    C = diag(sigma_scaled^2) + F phi F^T.

    The white part uses the EFAC/EQUAD-scaled uncertainties; the correlated
    part draws independent normal coefficients with the prior variances phi
    of every noise basis column (ECORR epoch blocks, power-law red/DM Fourier
    modes) and maps them through the basis — the same covariance the GLS
    fitter models, so GLS closure tests can inject exactly what they fit
    (reference simulation.py:273-311). ``include_common=False`` leaves the
    common GWB process out of the draw — the PTA injection flow draws it
    HD-correlated across the array with `add_gwb_to_arrays` instead."""
    rng = rng or np.random.default_rng()
    res = Residuals(toas, model, subtract_mean=False)
    n = len(toas)
    sigma = np.asarray(model.scaled_sigma(model.params, res.tensor))[:n]
    shift = rng.standard_normal(n) * sigma
    basis = model.noise_basis_and_weights(model.params, res.tensor,
                                          include_common=include_common)
    if basis is not None:
        import jax.numpy as jnp

        from pint_tpu.fitting.woodbury import basis_matvec

        ae = ad = None
        if basis.ephi is not None:
            ae = jnp.asarray(
                rng.standard_normal(basis.ke) * np.sqrt(np.asarray(basis.ephi))
            )
        if basis.dense_phi is not None:
            ad = jnp.asarray(
                rng.standard_normal(basis.kd)
                * np.sqrt(np.asarray(basis.dense_phi))
            )
        shift = shift + np.asarray(basis_matvec(basis, ae, ad))[:n]
    return _reprepare(toas, shift)


def add_gwb_to_arrays(toas_list, models, rng=None):
    """Shift an N-pulsar array of TOA sets by ONE Hellings-Downs-
    correlated realization of the common GWB process the models carry
    (models/noise.py PLGWBNoise).

    The draw is the Cholesky of the coefficient prior
    ORF (x) diag(phi_gw) on the SHARED Fourier basis: independent
    normal mode coefficients xi_a scaled by sqrt(phi_gw) are mixed
    across pulsars by chol(ORF) — cov(a_a, a_b) = Gamma_ab diag(phi) —
    and mapped through each pulsar's common-basis block G_a evaluated
    on the array-wide span. Exactly the covariance the joint PTA
    likelihood (fitting/pta_like.py) marginalizes, so GWB
    injection/recovery closes without reference data
    (validation/gwb_recovery.py). Per-pulsar noise (white, ECORR,
    pulsar red noise) is NOT drawn here — compose with
    `add_noise_from_model` per pulsar; its basis draw must then exclude
    the common component, which this function's companion flow in the
    validation harness handles by drawing from models without TNGW*.

    Returns the shifted TOAs list (same order)."""
    from pint_tpu.models.noise import orf_matrix, pulsar_position

    rng = rng or np.random.default_rng()
    if len(toas_list) != len(models):
        raise ValueError("toas_list and models must pair up")
    comps = [m.common_noise_component for m in models]
    if any(c is None for c in comps):
        raise ValueError("every model needs a common GWB component "
                         "(TNGWAMP/TNGWGAM) to draw a correlated GWB")
    nf = comps[0].nf
    if any(c.nf != nf for c in comps):
        raise ValueError("array common-process mode counts differ")
    n = len(models)
    orf = orf_matrix(np.stack([pulsar_position(m) for m in models]))
    L = np.linalg.cholesky(orf)

    # the shared span + per-pulsar time columns, in the common absolute
    # t convention (tensor t_hi: TDB seconds since the tensor epoch)
    res = [Residuals(t, m, subtract_mean=False)
           for t, m in zip(toas_list, models)]
    t_cols, lo, hi = [], np.inf, -np.inf
    for t, r, m in zip(toas_list, res, models):
        tc = np.asarray(r.tensor["t_hi"])[: len(t)]
        real = np.asarray(t.error_us) > 0
        tr = tc[real] if real.any() else tc
        lo, hi = min(lo, tr.min()), max(hi, tr.max())
        t_cols.append(tc)
    tspan = hi - lo

    import jax.numpy as jnp

    from pint_tpu.models.noise import fourier_basis

    m_modes = 2 * nf
    freqs = np.repeat(np.linspace(1.0 / tspan, nf / tspan, nf), 2)
    phi = np.asarray(comps[0].gwb_weights(models[0].params,
                                          jnp.asarray(freqs)))
    xi = rng.standard_normal((n, m_modes)) * np.sqrt(phi)
    coeff = L @ xi  # (N, m): HD-mixed mode coefficients
    out = []
    for a, (t, m) in enumerate(zip(toas_list, models)):
        G, _ = fourier_basis(jnp.asarray(t_cols[a]), nf, tspan)
        out.append(_reprepare(t, np.asarray(G) @ coeff[a]))
    return out


def make_fake_toas_fromtim(timfile: str, model, add_noise: bool = False, rng=None) -> TOAs:
    """Fakes at the epochs/errors/freqs of an existing tim file (reference
    simulation.py make_fake_toas_fromtim)."""
    from pint_tpu.toas import get_TOAs

    real = get_TOAs(timfile, model=model)
    toas = zero_residuals(real, model)
    if add_noise:
        rng = rng or np.random.default_rng()
        toas = _reprepare(toas, rng.standard_normal(len(toas)) * toas.error_us * 1e-6)
    return toas


def calculate_random_models(fitter, toas, n_models: int = 100, rng=None):
    """Residual predictions for parameter vectors drawn from the fit
    covariance (reference utils.calculate_random_models) — the draw
    evaluates as ONE vmapped jitted program over the model batch.

    Returns (dphase (n_models, ntoa) phase residuals, draws (n_models, p)).
    """
    import jax
    import jax.numpy as jnp

    from pint_tpu.fitting.wls import apply_delta
    from pint_tpu.residuals import phase_residual_frac

    res = fitter.result
    if res is None or res.covariance is None:
        raise RuntimeError("run fit_toas first")
    rng = rng or np.random.default_rng()
    free = tuple(res.free_params)
    draws = rng.multivariate_normal(np.zeros(len(free)), res.covariance, n_models)

    model = fitter.model
    # reuse the fitter's prepared residuals/tensor when it is the same TOA
    # set; only re-prepare for a different prediction epoch grid
    r = fitter.resids if toas is fitter.toas else Residuals(toas, model)
    if hasattr(r, "toa"):
        r = r.toa
    params = model.xprec.convert_params(model.params)

    def one(delta):
        _, rr, f = phase_residual_frac(
            model, apply_delta(params, free, delta), r.tensor,
            track_pn=r._track_pn, delta_pn=r._delta_pn,
            subtract_mean=r.subtract_mean, weights=r._weights,
        )
        return rr

    from pint_tpu.ops.compile import precision_jit

    fn = precision_jit(jax.vmap(one))
    return np.asarray(fn(jnp.asarray(draws))), draws


def monte_carlo_uncertainty(
    fitter,
    n_realizations: int = 32,
    rng: np.random.Generator | None = None,
    correlated: bool = False,
    mesh=None,
    maxiter: int = 30,
    batch_axis: str = "batch",
    toa_axis: str = "toa",
) -> dict:
    """Monte-Carlo parameter uncertainties by refitting fake-TOA
    realizations — run as ONE fleet fit (fitting/batch.py).

    Where `calculate_random_models` samples the LINEARIZED covariance,
    this is the full nonlinear bootstrap: fakes are generated exactly on
    the fitted model (`zero_residuals` once), each realization draws
    fresh noise (white from the TOA errors, or the model's full noise
    covariance with ``correlated=True``) through `_reprepare`'s
    geometry-reuse fast path, and every realization is refit from the
    fitted parameters. All B refits run as one batched fused LM program
    (same skeleton, same bucket → one compile), optionally sharded over a
    (batch, toa) mesh (`distributed.batch_fit_mesh`).

    Returns ``{"free", "draws" (B, p) fitted values, "mean", "scatter"
    (per-parameter std), "fitted" (the original fit's values),
    "uncertainties" (the original fit's formal sigmas), "results"}``.
    """
    import copy

    from pint_tpu.fitting.batch import fit_batch
    from pint_tpu.models.base import leaf_to_f64

    if fitter.result is None:
        raise RuntimeError("run fit_toas first")
    rng = rng or np.random.default_rng()
    model = fitter.model
    free = tuple(fitter.result.free_params)
    base = zero_residuals(fitter.toas, model)
    n = len(base)
    fleet = []
    for _ in range(n_realizations):
        if correlated:
            toas_i = add_noise_from_model(base, model, rng=rng)
        else:
            toas_i = _reprepare(
                base, rng.standard_normal(n) * base.error_us * 1e-6)
        fleet.append(type(fitter)(toas_i, copy.deepcopy(model)))
    results = fit_batch(fleet, maxiter=maxiter, mesh=mesh,
                        batch_axis=batch_axis, toa_axis=toa_axis)
    draws = np.array([
        [float(np.asarray(leaf_to_f64(f.model.params[p]))) for p in free]
        for f in fleet
    ])
    fitted = np.array([
        float(np.asarray(leaf_to_f64(model.params[p]))) for p in free
    ])
    return {
        "free": list(free),
        "draws": draws,
        "mean": draws.mean(axis=0),
        "scatter": draws.std(axis=0, ddof=1) if n_realizations > 1
        else np.zeros(len(free)),
        "fitted": fitted,
        "uncertainties": np.array([
            fitter.result.uncertainties[p] for p in free
        ]),
        "results": results,
    }
