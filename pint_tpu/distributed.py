"""Multi-host initialization and mesh construction.

SURVEY.md §2.9 communication backend: the reference scales across hosts
with MPI/NCCL process groups; the TPU-native equivalent is
`jax.distributed` + XLA collectives over ICI/DCN. This module is the
package's entry point for that path:

- :func:`initialize` — one call per process before any jax computation;
  on TPU pods every argument is auto-detected from the runtime, on
  CPU/GPU clusters pass coordinator/process counts explicitly (mirrors
  `jax.distributed.initialize`, with eager validation so misconfigured
  jobs fail at the call site, not in a collective timeout later).
- :func:`global_mesh` — a named `jax.sharding.Mesh` over every device of
  every process (with `-1` wildcard sizing, like a reshape).
- :func:`process_info` — process/device topology of the running job.

A multi-host chi^2 grid then needs NO new code: `gridutils.grid_chisq`
accepts any Mesh whose axes name the grid/toa shardings, and under jit the
psums it emits ride ICI within a host and DCN across hosts:

    import pint_tpu.distributed as dist
    dist.initialize()                       # no-op single-process
    mesh = dist.global_mesh({"grid": -1, "toa": 1})
    grid_chisq(ftr, ("M2", "SINI"), grids, mesh=mesh,
               grid_axis="grid", toa_axis="toa")

Every process runs the same script; each computes the full (replicated)
small outputs and its own shard of the grid axis.
"""

from __future__ import annotations

import os

from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.distributed")

__all__ = ["batch_fit_mesh", "initialize", "fit_mesh", "global_mesh",
           "process_info", "pta_mesh"]


def _init_args(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> dict:
    """Validated kwargs for `jax.distributed.initialize`.

    Pure assembly/validation (unit-testable without a cluster): either
    ALL of coordinator/num_processes/process_id are given explicitly, or
    NONE are and the runtime must auto-detect (TPU pods, SLURM, and Open
    MPI environments do; anything else raises here rather than hanging in
    the coordinator handshake)."""
    explicit = {
        "coordinator_address": coordinator_address,
        "num_processes": num_processes,
        "process_id": process_id,
    }
    given = {k: v for k, v in explicit.items() if v is not None}
    if given and len(given) != 3:
        missing = sorted(set(explicit) - set(given))
        raise ValueError(
            f"explicit multi-process init needs coordinator_address, "
            f"num_processes AND process_id; missing {missing}"
        )
    if num_processes is not None and num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    if process_id is not None and not (0 <= process_id < (num_processes or 1)):
        raise ValueError(
            f"process_id {process_id} outside [0, {num_processes})"
        )
    if coordinator_address is not None and ":" not in coordinator_address:
        raise ValueError(
            f"coordinator_address must be host:port, got {coordinator_address!r}"
        )
    args = dict(given)
    if local_device_ids is not None:
        args["local_device_ids"] = list(local_device_ids)
    if not given:
        if local_device_ids is not None:
            raise ValueError(
                "local_device_ids without coordinator_address/num_processes/"
                "process_id would start an uncoordinated handshake; pass the "
                "full explicit triple (or none, for autodetection)"
            )
        # environments jax.distributed can auto-detect a topology from.
        # NOTE: GCE TPU-VM pods can also be detected through the metadata
        # server with none of these exported — pass force=True to
        # initialize() there (documented on the function).
        markers = ("TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID",
                   "TPU_PROCESS_BOUNDS", "TPU_WORKER_ID",
                   "MEGASCALE_COORDINATOR_ADDRESS",
                   "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE")
        # external cluster-engine markers, not pint_tpu knobs: the names
        # are owned by the TPU runtime / SLURM / Open MPI
        args["_autodetect"] = any(os.environ.get(m) for m in markers)  # jaxlint: disable=env-read
    return args


_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
    force: bool = False,
) -> None:
    """Connect this process to the jax distributed runtime (idempotent).

    Call once per process, BEFORE the first jax computation. With no
    arguments: a no-op in a single-process environment, auto-detected
    topology when an env marker shows one (TPU pod vars, SLURM, Open
    MPI). On GCE TPU-VM pods whose topology only the metadata server
    knows (no env markers), pass ``force=True`` to hand detection to
    `jax.distributed.initialize` unconditionally. Explicit arguments
    follow `jax.distributed.initialize`."""
    global _initialized
    if _initialized:
        log.info("distributed runtime already initialized; skipping")
        return
    args = _init_args(coordinator_address, num_processes, process_id,
                      local_device_ids)
    auto = args.pop("_autodetect", None)
    if not args and auto is False and not force:
        log.info("single-process environment (no coordinator/autodetect); "
                 "skipping jax.distributed — force=True overrides")
        return
    import jax

    try:
        jax.distributed.initialize(**args)
    except (RuntimeError, ValueError) as e:
        if args:  # explicit configuration must fail loudly
            raise
        # autodetect marker was a false positive (e.g. a single-host
        # tunnel exporting TPU_WORKER_HOSTNAMES, where no cluster engine
        # resolves a coordinator) or the backend was already up: stay
        # single-process rather than killing the job
        log.warning(f"distributed autodetect declined ({e}); "
                    "continuing single-process")
        return
    _initialized = True
    log.info(
        f"distributed runtime up: process {jax.process_index()}/"
        f"{jax.process_count()}, {jax.local_device_count()} local / "
        f"{jax.device_count()} global devices"
    )


def global_mesh(axes: dict[str, int] | None = None, devices=None):
    """Named `jax.sharding.Mesh` over all global devices.

    `axes` maps axis name -> size; ONE size may be -1 (fills with the
    remaining devices, like reshape). Default: {"grid": -1} — shard the
    embarrassing axis, replicate TOAs. The axis order is the dict order
    (outermost first); put the axis that should ride the faster
    interconnect LAST (innermost = nearest devices)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    devices = np.asarray(devices if devices is not None else jax.devices())
    axes = dict(axes or {"grid": -1})
    sizes = list(axes.values())
    wild = [k for k, v in axes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"only one -1 axis allowed, got {wild}")
    known = 1
    for v in sizes:
        if v != -1:
            if v < 1:
                raise ValueError(f"axis sizes must be >= 1 or -1, got {axes}")
            known *= v
    if wild:
        if devices.size % known:
            raise ValueError(
                f"{devices.size} devices not divisible by {known} "
                f"(fixed axes of {axes})"
            )
        axes[wild[0]] = devices.size // known
    elif known != devices.size:
        raise ValueError(
            f"axes {axes} need {known} devices, have {devices.size}"
        )
    shape = tuple(axes.values())
    return Mesh(devices.reshape(shape), tuple(axes.keys()))


def fit_mesh(devices=None, axis: str = "toa"):
    """Single-axis mesh over every (global) device for TOA-sharded
    fitting — the layout `fit_toas()` shards its normal equations over
    (fitting/sharded.py). Returns None with fewer than two devices, so
    callers can pass the result straight to a fitter's `mesh=` argument
    and get the identical single-device program on one chip."""
    import jax

    devs = list(devices if devices is not None else jax.devices())
    if len(devs) < 2:
        return None
    return global_mesh({axis: -1}, devices=devs)


def batch_fit_mesh(devices=None, batch_axis: str = "batch",
                   toa_axis: str = "toa", batch: int | None = None,
                   toa: int | None = None):
    """2-D (batch, toa) mesh for fleet fitting (fitting/batch.py).

    The batch axis shards independent fleet elements (no collective —
    embarrassingly parallel); the toa axis shards each element's rows
    exactly as the single-fit sharded path, completing the per-element
    normal equations with one psum. Default layout puts every device on
    the batch axis (``{"batch": -1, "toa": 1}``); pass explicit sizes to
    trade batch parallelism for row parallelism (one of them may be -1).
    Returns None with fewer than two devices — the batched program then
    runs unsharded, same arithmetic.
    """
    import jax

    devs = list(devices if devices is not None else jax.devices())
    if len(devs) < 2:
        return None
    if batch is None and toa is None:
        batch, toa = -1, 1
    elif batch is None:
        batch = -1
    elif toa is None:
        toa = -1
    return global_mesh({batch_axis: batch, toa_axis: toa}, devices=devs)


def pta_mesh(n_pulsars: int, devices=None, batch_axis: str = "batch"):
    """Batch-axis mesh for the joint PTA likelihood (fitting/pta_like.py).

    The joint program shards PULSARS over the batch axis (per-pulsar
    Woodbury work is embarrassingly parallel; one psum completes the
    small coupling blocks), so the shard count must divide the pulsar
    count: this picks the LARGEST S <= device count with S | n_pulsars
    and lays the mesh over the first S global devices — on a multi-host
    pod (`initialize()` first) that takes N past one chip. Returns None
    when only one shard fits, so callers pass the result straight to
    ``PTALikelihood(mesh=...)`` and get the identical single-device
    program on one chip."""
    import jax

    devs = list(devices if devices is not None else jax.devices())
    s = max(min(len(devs), int(n_pulsars)), 1)
    while s > 1 and n_pulsars % s:
        s -= 1
    if s < 2:
        return None
    return global_mesh({batch_axis: s}, devices=devs[:s])


def process_info() -> dict:
    """Topology of the running job (single-process values when the
    distributed runtime is not up)."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
        "backend": jax.default_backend(),
        "initialized": _initialized,
    }
