"""pint_tpu: a TPU-native pulsar-timing framework.

A ground-up re-design of the capabilities of PINT (the pure-numpy/astropy
reference surveyed in SURVEY.md) for TPU hardware: the delay/phase chain of a
pulsar timing model is expressed as jit-compiled pure JAX functions using
double-double (compensated) arithmetic in place of 80/128-bit longdouble,
design matrices come from autodiff (jax.jacfwd) instead of ~2.4k LoC of
hand-written analytic partials, generalized-least-squares fits run on device,
and parameter grids / sampler ensembles scale over `jax.sharding.Mesh` axes
with XLA collectives.

Layering (mirrors SURVEY.md §1 but TPU-first):

- host side (numpy): parfile/tim parsing (`pint_tpu.io`), the astronomy
  environment (`pint_tpu.astro`: time scales, solar-system ephemeris, Earth
  rotation, observatories, clock chains) and TOA preparation (`pint_tpu.toas`)
  which ends in ONE host->device transfer of a dense "TOA tensor";
- device side (JAX): `pint_tpu.ops` (double-double arithmetic, Horner kernels,
  Kepler solvers), `pint_tpu.models` (the timing-model component chain as pure
  functions), `pint_tpu.residuals`, `pint_tpu.fitting` (WLS/GLS/downhill/
  wideband/MCMC), `pint_tpu.gridutils` (sharded chi^2 grids) and
  `pint_tpu.parallel` (mesh/sharding helpers).

Physical constants below follow the conventions of the reference
(`pint/__init__.py:56-103` defines ls, dmu, DMconst, Tsun; values here are
the same public IAU/CODATA numbers, TEMPO-compatible where the reference is).
"""

import jax

# Nanosecond pulse-phase precision requires float64 carriers for the
# double-double arithmetic everywhere; enable before any tracing happens.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the residual/fit/grid graphs take minutes
# to compile at 1e5-TOA scale, and every fresh process would otherwise pay
# that again. ops/compile.py owns the wiring (versioned directory under the
# shared cache root, utils/cache.py); PINT_TPU_COMPILE_CACHE overrides the
# location, "0" disables.
from pint_tpu.ops.compile import setup_persistent_cache as _setup_xla_cache  # noqa: E402

_setup_xla_cache()

__version__ = "0.1.0"

# --- fundamental constants (SI) ------------------------------------------------
C_M_PER_S = 299792458.0  # speed of light, exact
AU_M = 149597870700.0  # IAU 2012 astronomical unit, exact
AU_LS = AU_M / C_M_PER_S  # AU in light-seconds ~ 499.004784
PC_M = 3.0856775814913673e16  # IAU 2015 parsec, meters
PC_LS = PC_M / C_M_PER_S  # parsec in light-seconds
SECS_PER_DAY = 86400.0
DAYS_PER_JULIAN_YEAR = 365.25
SECS_PER_JULIAN_YEAR = SECS_PER_DAY * DAYS_PER_JULIAN_YEAR

# MJD epochs
MJD_J2000 = 51544.5  # TT epoch J2000.0 as an MJD
MJD_UNIX_EPOCH = 40587.0

# TEMPO-compatible dispersion constant, s MHz^2 / (pc cm^-3).  The reference
# deliberately uses 1/2.41e-4 instead of the CODATA e^2/(2 pi m_e c) value for
# TEMPO heritage compatibility (pint/__init__.py, "DMconst").
DMCONST = 1.0 / 2.41e-4  # = 4149.377593360996

# Solar-system GM / c^3 "mass in time units" (seconds).  Used by the Shapiro
# delay and binary post-Keplerian physics.  GM values are the DE-series /
# IAU-2015 nominal ones (public constants, not taken from the reference).
GM_SUN = 1.32712440041279419e20  # m^3/s^2 (DE440 heliocentric)
TSUN_S = GM_SUN / C_M_PER_S**3  # ~4.92549e-6 s

# GM per body in m^3/s^2 (DE440 nominal values).
GM_BODY = {
    "mercury": 2.2031868551e13,
    "venus": 3.24858592e14,
    "earth": 3.98600435507e14,
    "moon": 4.902800118e12,
    "mars": 4.2828375816e13,  # mars system
    "jupiter": 1.26712764100e17,  # jupiter system
    "saturn": 3.7940584841800e16,  # saturn system
    "uranus": 5.794556400e15,
    "neptune": 6.8365271005800e15,
}
TBODY_S = {k: v / C_M_PER_S**3 for k, v in GM_BODY.items()}

# Earth/Moon mass ratio (DE440)
EARTH_MOON_MASS_RATIO = 81.3005682214972154

# IAU 2006 obliquity of the ecliptic at J2000, arcseconds
OBLIQUITY_J2000_ARCSEC = 84381.406

from pint_tpu.utils.logging import get_logger  # noqa: E402

log = get_logger("pint_tpu")
