"""DMX helpers: window planning and post-fit extraction.

Reference: pint/utils.py (dmx_ranges:716 — propose DMX windows covering the
TOAs; dmxparse:893 — pull fitted DMX values/errors/epochs with the
covariance-corrected uncertainties used by NANOGrav).
"""

from __future__ import annotations

import numpy as np

from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.dmx")


def dmx_ranges(toas, bin_width_d: float = 6.5, pad_d: float = 0.05):
    """Greedy DMX windows covering every TOA (reference dmx_ranges:716
    semantics: consecutive TOAs group until the window would exceed
    bin_width days). Returns [(r1, r2), ...] MJD pairs."""
    mjd = np.sort(toas.tdb.mjd_float())
    bounds = []
    start = prev = mjd[0]
    for t in mjd[1:]:
        if t - start > bin_width_d:
            bounds.append((start, prev))
            start = t
        prev = t
    bounds.append((start, prev))
    # pad, clamping to half the gap between neighbors so windows never
    # overlap (overlap would double-apply DM to boundary TOAs)
    ranges = []
    for i, (a, b) in enumerate(bounds):
        lo_pad = pad_d if i == 0 else min(pad_d, (a - bounds[i - 1][1]) / 2.0)
        hi_pad = pad_d if i == len(bounds) - 1 else min(pad_d, (bounds[i + 1][0] - b) / 2.0)
        ranges.append((a - lo_pad, b + hi_pad))
    return ranges


def add_dmx_to_model(model, ranges) -> None:
    """Install DMX windows (all values 0, free) on a model (reference
    utils.dmx_setup flow)."""
    from pint_tpu.models.dispersion import DispersionDMX
    from pint_tpu.models.parameter import ParamValueMeta

    comp = next((c for c in model.components if isinstance(c, DispersionDMX)), None)
    if comp is None:
        comp = DispersionDMX()
        model.components.append(comp)
        from pint_tpu.models.base import DEFAULT_ORDER

        order = {cat: i for i, cat in enumerate(DEFAULT_ORDER)}
        model.components.sort(key=lambda c: order.get(c.category, 99))
    for i, (r1, r2) in enumerate(ranges, start=1):
        comp.add_window(i, float(r1), float(r2))
        spec = comp.specs[f"DMX_{i:04d}"]
        model.params[spec.name] = 0.0
        model.param_meta[spec.name] = ParamValueMeta(spec=spec, frozen=False)
    model.clear_caches()  # structural change: new component/columns


def dmx_batch_refit(fitter, ranges=None, bin_width_d: float = 6.5,
                    mesh=None, maxiter: int = 20,
                    batch_axis: str = "batch", toa_axis: str = "toa") -> dict:
    """Per-window DMX refits as ONE fleet fit (fitting/batch.py).

    Each window becomes an independent mini-fit: the window's TOAs, a
    copy of the model with every timing parameter frozen and a SINGLE
    free DMX window covering the range — so all windows share one model
    skeleton and batch into bucketed fused LM programs despite ragged
    per-window TOA counts. This is the NANOGrav dmxparse workflow turned
    into a batched-serving workload: B windows, one (or a few) compiled
    programs, one device sync.

    `ranges` defaults to `dmx_ranges(toas, bin_width_d)`. Returns the
    dmxparse-shaped dict (dmxs / dmx_verrs / dmx_epochs / r1s / r2s)
    plus the per-window FitResults and TOA counts.
    """
    import copy

    from pint_tpu.fitting.batch import fit_batch
    from pint_tpu.fitting.wls import DownhillWLSFitter
    from pint_tpu.models.dispersion import DispersionDMX

    model = fitter.model
    toas = fitter.toas
    if ranges is None:
        ranges = dmx_ranges(toas, bin_width_d=bin_width_d)
    mjd = toas.tdb.mjd_float()

    def window_model(r1, r2):
        m = copy.deepcopy(model)
        for c in [c for c in m.components if isinstance(c, DispersionDMX)]:
            for name in list(c.specs):
                m.params.pop(name, None)
                m.param_meta.pop(name, None)
            m.components.remove(c)
        for meta in m.param_meta.values():
            meta.frozen = True  # timing solution held fixed per window
        add_dmx_to_model(m, [(r1, r2)])
        return m

    kept, fleet = [], []
    for r1, r2 in ranges:
        sel = (mjd >= r1) & (mjd <= r2)
        if not sel.any():
            continue
        kept.append((r1, r2))
        fleet.append(DownhillWLSFitter(toas.select(sel), window_model(r1, r2)))
    if not fleet:
        raise ValueError("no DMX window contains any TOA")
    results = fit_batch(fleet, maxiter=maxiter, mesh=mesh,
                        batch_axis=batch_axis, toa_axis=toa_axis)
    r1s = np.array([r[0] for r in kept])
    r2s = np.array([r[1] for r in kept])
    return {
        "dmxs": np.array([
            float(np.asarray(f.model.params["DMX_0001"])) for f in fleet
        ]),
        "dmx_verrs": np.array([
            r.uncertainties.get("DMX_0001", np.nan) for r in results
        ]),
        "dmx_epochs": 0.5 * (r1s + r2s),
        "r1s": r1s,
        "r2s": r2s,
        "ntoas": np.array([len(f.resids.errors_s) for f in fleet]),
        "results": results,
    }


def dmxparse(fitter) -> dict:
    """Fitted DMX time series with covariance-corrected errors (reference
    dmxparse:893: verr_i = sqrt(var_i + mean-DMX variance - 2 cov_i,mean),
    accounting for the overall-DM degeneracy)."""
    model = fitter.model
    res = fitter.result
    if res is None:
        raise RuntimeError("run fit_toas first")
    from pint_tpu.models.dispersion import DispersionDMX

    comp = next((c for c in model.components if isinstance(c, DispersionDMX)), None)
    if comp is None:
        raise ValueError("model has no DMX component")
    idxs = comp.sorted_indices
    names = [f"DMX_{i:04d}" for i in idxs]
    free = list(res.free_params)
    vals = np.array([float(np.asarray(model.params[n])) for n in names])
    r1 = np.array([comp.windows[i][0] for i in idxs])
    r2 = np.array([comp.windows[i][1] for i in idxs])
    eps = 0.5 * (r1 + r2)
    out = {
        "dmxs": vals,
        "dmx_epochs": eps,
        "r1s": r1,
        "r2s": r2,
        "dmx_verrs": np.full(len(names), np.nan),
        "mean_dmx": float(np.mean(vals)),
    }
    if res.covariance is not None and all(n in free for n in names):
        ii = np.array([free.index(n) for n in names])
        C = res.covariance[np.ix_(ii, ii)]
        var = np.diag(C)
        # variance of the mean and covariance of each with the mean
        var_mean = float(np.sum(C)) / len(names) ** 2
        cov_with_mean = np.sum(C, axis=1) / len(names)
        out["dmx_verrs"] = np.sqrt(var + var_mean - 2.0 * cov_with_mean)
        out["mean_dmx_verr"] = float(np.sqrt(var_mean))
    return out
