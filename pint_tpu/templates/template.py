"""Pulse-profile template: a normalized mixture of primitive components
plus a uniform unpulsed background.

Reference: pint/templates/lctemplate.py (1,077 LoC). Density over phase
x in [0,1):

    f(x) = (1 - sum_i ampl_i) + sum_i ampl_i * comp_i(x)

with each comp_i a unit-normalized primitive (primitives.py) and the
amplitudes a point of the simplex (norms.py). Each component owns its
amplitude — the NormAngles view is constructed on demand (`norm_angles`)
for simplex-space manipulation, and the fitters parametrize amplitudes
through the same angle map, so sum <= 1 holds by construction during fits.
"""

from __future__ import annotations

import re
from dataclasses import replace

import numpy as np

from pint_tpu.templates.norms import NormAngles
from pint_tpu.templates.primitives import (
    FWHM_TO_SIGMA,
    LCGaussian,
    LCLorentzian,
    LCPrimitive,
)

__all__ = [
    "LCTemplate",
    "GaussianPrior",
    "get_gauss1",
    "get_gauss2",
    "get_2pb",
]


class LCTemplate:
    """Mixture-of-primitives profile (see module docstring).

    Constructed from a list of primitives (each carrying its `ampl`); the
    original round-2 API (list of LCGaussian dataclasses) is unchanged.
    """

    def __init__(self, components: list | None = None):
        self.components = list(components or [])

    # --- original surface (kept stable for event_optimize/photonphase) --------

    @property
    def primitives(self) -> list:
        return self.components

    def __getitem__(self, i):
        return self.components[i]

    def __len__(self) -> int:
        return len(self.components)

    @property
    def total_ampl(self) -> float:
        return float(sum(c.ampl for c in self.components))

    def norm(self) -> float:
        """Pulsed fraction (reference LCTemplate.norm)."""
        return self.total_ampl

    def __call__(self, phases, log10_ens=None) -> np.ndarray:
        """Normalized profile density at phases (cycles)."""
        x = np.mod(np.asarray(phases, float), 1.0)
        out = np.full_like(x, max(1.0 - self.total_ampl, 0.0))
        for c in self.components:
            if log10_ens is not None and hasattr(c, "density_e"):
                out = out + c.ampl * c.density_e(x, log10_ens)
            else:
                out = out + c.ampl * c.density(x)
        return out

    def shifted(self, dphi: float) -> "LCTemplate":
        return LCTemplate(
            [replace(c, phase=(c.phase + dphi) % 1.0) for c in self.components]
        )

    # --- component manipulation (reference lctemplate component API) ----------

    def rotate(self, dphi: float) -> None:
        """In-place phase rotation of every component (reference
        LCTemplate.rotate — note our sign: new_phase = phase + dphi)."""
        for c in self.components:
            c.phase = (c.phase + dphi) % 1.0

    def set_overall_phase(self, phase: float) -> None:
        """Rotate so the FIRST component sits at `phase` (reference
        LCTemplate.set_overall_phase)."""
        if not self.components:
            return
        self.rotate(phase - self.components[0].phase)

    def get_location(self) -> float:
        return self.components[0].phase if self.components else 0.0

    def get_display_point(self) -> float:
        """Phase that centers the profile for display: half a cycle from
        the amplitude-weighted circular mean of component locations."""
        if not self.components:
            return 0.5
        z = sum(c.ampl * np.exp(2j * np.pi * c.phase) for c in self.components)
        mean = (np.angle(z) / (2 * np.pi)) % 1.0
        return (mean + 0.5) % 1.0

    def add_primitive(self, prim: LCPrimitive) -> None:
        self.components.append(prim)

    def delete_primitive(self, index: int) -> "LCPrimitive":
        """Remove a component; its amplitude returns to the background."""
        return self.components.pop(index)

    def order_primitives(self, order: int = 0) -> None:
        """Sort components by location (order=0) or amplitude (order=1)."""
        key = (lambda c: c.phase) if order == 0 else (lambda c: -c.ampl)
        self.components.sort(key=key)

    def norm_angles(self) -> NormAngles:
        """Amplitudes as a NormAngles simplex object (lcnorm surface)."""
        return NormAngles([c.ampl for c in self.components])

    def set_norms(self, norms) -> None:
        norms = np.asarray(norms, float)
        if norms.sum() > 1.0 + 1e-9:
            raise ValueError("norms sum past 1")
        for c, n in zip(self.components, norms):
            c.ampl = float(n)

    def copy(self) -> "LCTemplate":
        return LCTemplate([c.copy() for c in self.components])

    def is_energy_dependent(self) -> bool:
        return any(hasattr(c, "density_e") for c in self.components)

    # --- integration / cdf / sampling -----------------------------------------

    def integrate(self, x1, x2, log10_ens=None) -> np.ndarray | float:
        """Integral of the density over [x1, x2] (wrapping when x2 < x1 is
        interpreted as the signed integral, matching the reference)."""
        x1a = np.atleast_1d(np.asarray(x1, float))
        x2a = np.atleast_1d(np.asarray(x2, float))
        out = np.array([self._cdf_scalar(b) - self._cdf_scalar(a)
                        for a, b in zip(x1a, x2a)])
        return out if np.ndim(x1) else float(out[0])

    def _cdf_scalar(self, x: float) -> float:
        # piece together whole cycles + the fractional part on a fine grid
        whole, frac = divmod(x, 1.0)
        grid = np.linspace(0, frac, max(int(1024 * frac), 2))
        val = np.trapezoid(self(grid), grid) if frac > 0 else 0.0
        return whole + val

    def cdf(self, x, log10_ens=None):
        x = np.asarray(x, float)
        return self.integrate(np.zeros_like(x), x)

    def random(self, n: int, rng=None, weights=None) -> np.ndarray:
        """Draw n photon phases from the profile (reference
        LCTemplate.random): each component by its own sampler fraction,
        the rest uniform background."""
        rng = rng or np.random.default_rng()
        ampls = np.array([c.ampl for c in self.components])
        probs = np.append(ampls, max(1.0 - ampls.sum(), 0.0))
        probs = probs / probs.sum()
        which = rng.choice(len(probs), size=n, p=probs)
        out = rng.uniform(size=n)  # background default
        grid = np.linspace(0, 1, 2048, endpoint=False)
        for i, c in enumerate(self.components):
            m = which == i
            if not m.any():
                continue
            dens = np.maximum(c.density(grid), 0)
            cdf = np.cumsum(dens)
            cdf = cdf / cdf[-1]
            out[m] = np.interp(rng.uniform(size=int(m.sum())), cdf, grid)
        return np.mod(out, 1.0)

    # --- parameter vector surface (used by LCFitter) --------------------------

    def get_errors(self) -> dict:
        errs = {}
        for k, c in enumerate(self.components, start=1):
            for name, val in getattr(c, "fit_errors", {}).items():
                errs[f"{name}{k}"] = val
        return errs

    # --- 'gauss' text format (reference lctemplate.prim_io:1009) --------------

    @classmethod
    def read(cls, path: str) -> "LCTemplate":
        """Read the reference's 'gauss' text template format, including
        per-parameter errors; recognizes gaussian ('# gauss') and, for
        forward compatibility, von Mises ('# vonmises') component blocks."""
        with open(path) as f:
            text = f.read()
        return cls.parse(text)

    @classmethod
    def parse(cls, text: str) -> "LCTemplate":
        from pint_tpu.templates.primitives import LCVonMises

        kind = "gauss"
        m = re.search(r"#\s*(\w+)", text)
        if m:
            kind = m.group(1).lower()
        prim_cls = {"gauss": LCGaussian, "vonmises": LCVonMises}.get(kind, LCGaussian)
        vals: dict[str, float] = {}
        errs: dict[str, float] = {}
        for line in text.splitlines():
            mm = re.match(
                r"\s*(\w+)\s*=\s*([-\d.eE+]+)(?:\s*\+/-\s*([-\d.eE+]+))?", line
            )
            if mm:
                vals[mm.group(1)] = float(mm.group(2))
                if mm.group(3) is not None:
                    errs[mm.group(1)] = float(mm.group(3))
        comps = []
        k = 1
        while f"phas{k}" in vals:
            c = prim_cls(vals[f"phas{k}"], vals[f"fwhm{k}"], vals[f"ampl{k}"])
            fe = {
                n: errs[f"{n}{k}"]
                for n in ("phas", "fwhm", "ampl")
                if f"{n}{k}" in errs
            }
            if fe:
                c.fit_errors = fe
            comps.append(c)
            k += 1
        if not comps:
            raise ValueError("no components found in template text")
        return cls(comps)

    def write(self, path: str) -> None:
        """Write the 'gauss'/'vonmises' text format. Raises at WRITE time
        for component mixes the text format cannot round-trip (the generic
        __str__ rendering is display-only and unreadable by read())."""
        from pint_tpu.templates.primitives import LCVonMises

        if not (all(isinstance(c, LCGaussian) for c in self.components)
                or all(isinstance(c, LCVonMises) for c in self.components)):
            raise TypeError(
                "the template text format represents all-Gaussian or "
                "all-von-Mises profiles only; use pickle for "
                f"{sorted({type(c).__name__ for c in self.components})}"
            )
        with open(path, "w") as f:
            f.write(str(self) + "\n")

    def __str__(self) -> str:
        from pint_tpu.templates.primitives import LCVonMises

        if self.components and all(
            isinstance(c, LCVonMises) for c in self.components
        ):
            return self._str_block("vonmises")
        for c in self.components:
            if not isinstance(c, LCGaussian):
                return self._str_generic()
        return self._str_block("gauss")

    def _str_block(self, kind: str) -> str:
        lines = [f"# {kind}", "-" * 25]
        bg_err = 0.0
        lines.append(f"const = {max(1.0 - self.total_ampl, 0.0):.5f} +/- {bg_err:.5f}")
        for k, c in enumerate(self.components, start=1):
            fe = getattr(c, "fit_errors", {})
            lines.append(f"phas{k} = {c.phase:.5f} +/- {fe.get('phas', 0.0):.5f}")
            lines.append(f"fwhm{k} = {c.fwhm:.5f} +/- {fe.get('fwhm', 0.0):.5f}")
            lines.append(f"ampl{k} = {c.ampl:.5f} +/- {fe.get('ampl', 0.0):.5f}")
        lines.append("-" * 25)
        return "\n".join(lines)

    def _str_generic(self) -> str:
        lines = [f"# {type(self).__name__}"]
        for k, c in enumerate(self.components, start=1):
            fe = getattr(c, "fit_errors", {})
            lines.append(f"component {k}: {type(c).__name__}")
            lines.append(f"  phas = {c.phase:.5f} +/- {fe.get('phas', 0.0):.5f}")
            for n in c.shape_names:
                lines.append(
                    f"  {n} = {getattr(c, n):.5f} +/- {fe.get(n, 0.0):.5f}"
                )
            lines.append(f"  ampl = {c.ampl:.5f} +/- {fe.get('ampl', 0.0):.5f}")
        return "\n".join(lines)


class GaussianPrior:
    """Independent Gaussian priors on a subset of fit parameters
    (reference lctemplate.GaussianPrior:975). Call with the fitter's
    physical parameter vector; returns -log prior (added to the NLL)."""

    def __init__(self, locations, widths, mask):
        self.loc = np.asarray(locations, float)
        self.width = np.asarray(widths, float)
        self.mask = np.asarray(mask, bool)

    def __len__(self) -> int:
        return int(self.mask.sum())

    def __call__(self, p) -> float:
        import jax.numpy as jnp

        d = (jnp.asarray(p)[self.mask] - self.loc) / self.width
        return 0.5 * jnp.sum(d * d)


# --- factories (reference lctemplate.get_gauss1/get_gauss2/get_2pb) -----------


def get_gauss1(pulse_frac: float = 1.0, x1: float = 0.5, width1: float = 0.01) -> LCTemplate:
    return LCTemplate([LCGaussian(x1, width1 / FWHM_TO_SIGMA, pulse_frac)])


def get_gauss2(
    pulse_frac: float = 1.0,
    x1: float = 0.1,
    x2: float = 0.55,
    ratio: float = 1.5,
    width1: float = 0.01,
    width2: float = 0.02,
) -> LCTemplate:
    """Two-Gaussian profile; `ratio` = ampl1/ampl2, widths are sigmas in
    cycles (converted to fwhm internally), matching the reference factory."""
    a1 = ratio * pulse_frac / (1.0 + ratio)
    a2 = pulse_frac / (1.0 + ratio)
    return LCTemplate(
        [
            LCGaussian(x1, width1 / FWHM_TO_SIGMA, a1),
            LCGaussian(x2, width2 / FWHM_TO_SIGMA, a2),
        ]
    )


def get_2pb(pulse_frac: float = 0.9, lorentzian: bool = False) -> LCTemplate:
    """Canonical two-peak-and-bridge gamma-pulsar shape."""
    cls = LCLorentzian if lorentzian else LCGaussian
    return LCTemplate(
        [
            cls(0.1, 0.03, 0.3 * pulse_frac),
            cls(0.3, 0.15, 0.2 * pulse_frac),  # the bridge
            cls(0.55, 0.03, 0.5 * pulse_frac),
        ]
    )
