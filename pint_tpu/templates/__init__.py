"""Photon pulse-profile templates: primitives, normalization simplex,
energy dependence, and maximum-likelihood fitters.

Reference: pint/templates/ (~4.8k LoC across lcprimitives.py,
lcnorm.py, lctemplate.py, lcfitters.py, lceprimitives.py, lcenorm.py).
Layout here:

- primitives.py — component shapes (Gaussian, two-sided/skew Gaussian,
  Lorentzian(2), von Mises, King, top-hat, harmonic, KDE, empirical
  Fourier) as pure jax densities; all derivatives via autodiff.
- norms.py — NormAngles/ENormAngles amplitude simplex (sum <= 1 by
  construction).
- template.py — LCTemplate mixture + IO ('gauss' format), factories,
  GaussianPrior.
- fitters.py — LCFitter (unbinned/binned weighted likelihood, hessian
  and bootstrap errors, position fits) + the original functional API
  (fit_template, fit_phase_shift, lnlikelihood, template_params,
  template_density_jnp) used by event_optimize and the photon CLIs.
- energy.py — energy-dependent primitive variants (LCEGaussian, ...).

Everything importable from the original flat module keeps working:
``from pint_tpu.templates import LCTemplate, LCGaussian, fit_template``.
"""

from pint_tpu.templates.energy import (
    LCEGaussian,
    LCEGaussian2,
    LCELorentzian,
    LCELorentzian2,
    LCESkewGaussian,
    LCEVonMises,
)
from pint_tpu.templates.fitters import (
    LCFitter,
    fit_phase_shift,
    fit_template,
    lnlikelihood,
    template_density_jnp,
    template_params,
    weighted_light_curve,
)
from pint_tpu.templates.norms import ENormAngles, NormAngles
from pint_tpu.templates.primitives import (
    FWHM_TO_SIGMA,
    LCEmpiricalFourier,
    LCGaussian,
    LCGaussian2,
    LCHarmonic,
    LCKernelDensity,
    LCKing,
    LCLorentzian,
    LCLorentzian2,
    LCPrimitive,
    LCSkewGaussian,
    LCTopHat,
    LCVonMises,
    convert_primitive,
)
from pint_tpu.templates.template import (
    GaussianPrior,
    LCTemplate,
    get_2pb,
    get_gauss1,
    get_gauss2,
)

__all__ = [
    "FWHM_TO_SIGMA",
    "ENormAngles",
    "GaussianPrior",
    "LCEGaussian",
    "LCEGaussian2",
    "LCELorentzian",
    "LCELorentzian2",
    "LCESkewGaussian",
    "LCEVonMises",
    "LCEmpiricalFourier",
    "LCFitter",
    "LCGaussian",
    "LCGaussian2",
    "LCHarmonic",
    "LCKernelDensity",
    "LCKing",
    "LCLorentzian",
    "LCLorentzian2",
    "LCPrimitive",
    "LCSkewGaussian",
    "LCTemplate",
    "LCTopHat",
    "LCVonMises",
    "NormAngles",
    "convert_primitive",
    "fit_phase_shift",
    "fit_template",
    "get_2pb",
    "get_gauss1",
    "get_gauss2",
    "lnlikelihood",
    "template_density_jnp",
    "template_params",
    "weighted_light_curve",
]
