"""Maximum-likelihood fitting of pulse-profile templates to photon phases.

Reference: pint/templates/lcfitters.py (1,084 LoC — LCFitter with unbinned
and binned weighted likelihoods, TNC/fmin drivers, hand-coded gradients,
hessian error estimation, bootstrap, position fits).

TPU-native redesign: the template likelihood is ONE pure jax function of an
unconstrained parameter vector
    theta = [phases | shape params (bounded-sigmoid) | norm angles]
— component amplitudes ride the NormAngles simplex map (norms.py), so
sum(ampl) <= 1 holds for ANY theta and the optimizer needs no barrier
terms. L-BFGS iterates on the host; gradient and Hessian come from
jax.grad / jax.hessian of the same jitted NLL (replacing the reference's
per-primitive hand-derivative layer), and parameter errors propagate
through the full transform jacobian to physical units.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.templates.norms import angles_from_norms, norms_from_angles_jnp
from pint_tpu.templates.primitives import FWHM_TO_SIGMA, LCGaussian, _WRAPS
from pint_tpu.templates.template import LCTemplate

__all__ = [
    "LCFitter",
    "weighted_light_curve",
    "fit_template",
    "fit_phase_shift",
    "lnlikelihood",
    "template_params",
    "template_density_jnp",
]


# --- original functional surface (kept stable; event_optimize depends on it) --


def template_params(template: LCTemplate):
    """(phases (k,), sigmas (k,), ampls (k,)) arrays of a pure-Gaussian
    template — the jit-friendly representation used by the photon-MCMC
    likelihood (event_optimize.py)."""
    for c in template.components:
        if not isinstance(c, LCGaussian):
            raise TypeError(
                "jitted template evaluation supports Gaussian components only"
            )
    return (
        np.array([c.phase for c in template.components]),
        np.array([c.fwhm * FWHM_TO_SIGMA for c in template.components]),
        np.array([c.ampl for c in template.components]),
    )


def template_density_jnp(x, phases, sigmas, ampls):
    """Normalized wrapped-Gaussian mixture density at phases x (jnp array,
    any shape; values taken mod 1) — the jax twin of LCTemplate.__call__."""
    import jax.numpy as jnp

    x = jnp.mod(x, 1.0)[..., None]
    out = jnp.zeros_like(x[..., 0]) + jnp.maximum(1.0 - jnp.sum(ampls), 0.0)
    for k in range(-_WRAPS, _WRAPS + 1):
        out = out + jnp.sum(
            ampls
            / (sigmas * np.sqrt(2 * np.pi))
            * jnp.exp(-0.5 * ((x - phases + k) / sigmas) ** 2),
            axis=-1,
        )
    return out


def lnlikelihood(template: LCTemplate, phases, weights=None, dphi: float = 0.0) -> float:
    """Unbinned weighted photon log-likelihood (reference lcfitters.py):
    sum log(w f(phi - dphi) + (1 - w))."""
    f = template(np.asarray(phases) - dphi)
    if weights is None:
        return float(np.sum(np.log(np.maximum(f, 1e-300))))
    w = np.asarray(weights)
    return float(np.sum(np.log(np.maximum(w * f + (1.0 - w), 1e-300))))


def fit_phase_shift(template: LCTemplate, phases, weights=None, n_grid: int = 256,
                    window: tuple | None = None):
    """Maximum-likelihood phase offset of the data vs the template, with a
    Fisher-information uncertainty (reference lcfitters.fit_position).
    `window=(lo, hi)` restricts the scan to shifts in that range (cycles,
    may span 0, e.g. (-0.2, 0.2) for tracking mode)."""
    if window is None:
        grid = np.linspace(0, 1, n_grid, endpoint=False)
        wrap = True
    else:
        grid = np.linspace(window[0], window[1], n_grid)
        wrap = False
    ll = np.array([lnlikelihood(template, phases, weights, d) for d in grid])
    i = int(np.argmax(ll))
    step = grid[1] - grid[0]
    # parabolic refinement around the grid peak (skipped at a hard window
    # edge, where the three-point stencil would cross the boundary)
    if wrap or 0 < i < n_grid - 1:
        lm, l0, lp = ll[(i - 1) % n_grid], ll[i], ll[(i + 1) % n_grid]
        denom = lm - 2 * l0 + lp
        frac = 0.5 * (lm - lp) / denom if denom != 0 else 0.0
        dphi = (grid[i] + frac * step) % 1.0
        curv = -denom / step**2  # d2(ll)/dphi2 -> -d2 for the NLL
        err = 1.0 / np.sqrt(curv) if curv > 0 else np.nan
    else:
        dphi, err, l0 = grid[i] % 1.0, np.nan, ll[i]
    return dphi, err, float(l0)


def weighted_light_curve(nbins: int, phases, weights=None, normed: bool = False,
                         phase_shift: float = 0.0):
    """(bin_edges, weighted counts, errors) of the photon light curve
    (reference lcfitters.weighted_light_curve:37)."""
    ph = np.mod(np.asarray(phases, float) - phase_shift, 1.0)
    w = np.ones_like(ph) if weights is None else np.asarray(weights, float)
    edges = np.linspace(0, 1, nbins + 1)
    idx = np.minimum((ph * nbins).astype(int), nbins - 1)
    counts = np.zeros(nbins)
    errs2 = np.zeros(nbins)
    np.add.at(counts, idx, w)
    np.add.at(errs2, idx, w * w)
    errs = np.sqrt(errs2)
    if normed:
        tot = counts.sum()
        counts, errs = counts / tot * nbins, errs / tot * nbins
    return edges, counts, errs


# --- the general theta <-> template transform ---------------------------------


class _Thetamap:
    """Bidirectional map between a template's free parameters and the
    unconstrained fit vector. Layout: [phases | shapes | norm angles].
    Shape params go through a bounded sigmoid onto their (lo, hi) bounds;
    amplitudes through the NormAngles angle map (sum <= 1 guaranteed)."""

    def __init__(self, template: LCTemplate, fit_shape: bool = True,
                 fit_position: bool = True, fit_norms: bool = True):
        self.template = template
        self.k = len(template.components)
        self.fit_shape = fit_shape
        self.fit_position = fit_position
        self.fit_norms = fit_norms
        self.shape_slices = []
        self.shape_bounds = []
        off = 0
        for c in template.components:
            nsh = len(c.shape_names) if fit_shape else 0
            self.shape_slices.append(slice(off, off + nsh))
            if fit_shape:
                self.shape_bounds.extend(c.shape_bounds)
            off += nsh
        self.nshape = off

    # physical -> unconstrained
    def theta0(self) -> np.ndarray:
        t = self.template
        parts = []
        if self.fit_position:
            parts.append(np.array([c.phase for c in t.components]))
        if self.fit_shape:
            vals = np.concatenate(
                [np.asarray(c.shape_values(), float) for c in t.components]
            ) if self.nshape else np.zeros(0)
            z = np.empty_like(vals)
            for i, (lo, hi) in enumerate(self.shape_bounds):
                f = np.clip((vals[i] - lo) / (hi - lo), 1e-6, 1 - 1e-6)
                z[i] = np.log(f / (1 - f))
            parts.append(z)
        if self.fit_norms:
            parts.append(angles_from_norms([c.ampl for c in t.components]))
        return np.concatenate(parts) if parts else np.zeros(0)

    def unpack(self, theta):
        """theta -> (phases (k,), shapes list-of-tuples, ampls (k,)) in
        jax-compatible form."""
        import jax.numpy as jnp

        t = self.template
        i = 0
        if self.fit_position:
            phases = theta[: self.k]
            i = self.k
        else:
            phases = jnp.asarray([c.phase for c in t.components])
        if self.fit_shape and self.nshape:
            z = theta[i : i + self.nshape]
            i += self.nshape
            svals = []
            for j, (lo, hi) in enumerate(self.shape_bounds):
                svals.append(lo + (hi - lo) / (1.0 + jnp.exp(-z[j])))
            shapes = [tuple(svals[s] for s in range(sl.start, sl.stop))
                      for sl in self.shape_slices]
        else:
            shapes = [tuple(jnp.asarray(v) for v in c.shape_values())
                      for c in t.components]
        if self.fit_norms:
            ampls = norms_from_angles_jnp(theta[i : i + self.k])
        else:
            ampls = jnp.asarray([c.ampl for c in t.components])
        return phases, shapes, ampls

    def density(self, theta, x, log10_ens=None):
        """Template density at photon phases x for fit vector theta.
        With `log10_ens`, energy-dependent components (those exposing
        `density_jnp_e_theta`) evaluate at the fitted phase/shapes shifted
        by their (fixed) energy slopes."""
        import jax.numpy as jnp

        phases, shapes, ampls = self.unpack(theta)
        out = jnp.maximum(1.0 - jnp.sum(ampls), 0.0) * jnp.ones_like(x)
        for j, c in enumerate(self.template.components):
            if log10_ens is not None and hasattr(c, "density_jnp_e_theta"):
                d = type(c).density_jnp_e_theta(
                    x, log10_ens, phases[j], shapes[j], jnp.asarray(c.slope)
                )
            else:
                d = c.density_jnp(x, phases[j], *shapes[j])
            out = out + ampls[j] * d
        return out

    def physical(self, theta):
        """theta -> flat physical vector [phases | shape values | ampls]
        (for error propagation through the transform jacobian)."""
        import jax.numpy as jnp

        phases, shapes, ampls = self.unpack(theta)
        flat_shapes = [s for tup in shapes for s in tup]
        return jnp.concatenate([
            jnp.asarray(phases),
            jnp.stack(flat_shapes) if flat_shapes else jnp.zeros(0),
            jnp.asarray(ampls),
        ])

    def write_back(self, theta, errors: np.ndarray | None = None) -> None:
        """Store fitted values (and physical-unit errors) on the template
        components; errors land in each component's `fit_errors` dict."""
        phases, shapes, ampls = (np.asarray(a) if not isinstance(a, list) else a
                                 for a in self.unpack(np.asarray(theta)))
        k = self.k
        # the physical vector ALWAYS carries every shape value (even when
        # fit_shape=False they enter as constants), so error offsets index
        # the cumulative physical layout, not the fit-vector layout
        n_shapes_total = sum(len(c.shape_names) for c in self.template.components)
        sh_phys_off = k
        for j, c in enumerate(self.template.components):
            c.phase = float(np.asarray(phases[j])) % 1.0
            for n, v in zip(c.shape_names, shapes[j]):
                setattr(c, n, float(np.asarray(v)))
            c.ampl = float(np.asarray(ampls[j]))
            if errors is not None:
                fe = {"phas": float(errors[j])}
                if self.fit_shape:
                    for m, n in enumerate(c.shape_names):
                        fe[n] = float(errors[sh_phys_off + m])
                fe["ampl"] = float(errors[k + n_shapes_total + j])
                c.fit_errors = fe
            sh_phys_off += len(c.shape_names)


class LCFitter:
    """Template fitter over photon phases (reference lcfitters.LCFitter:53).

    Parameters: template (LCTemplate, modified in place by fit), phases,
    optional weights, optional log10_ens (energy-dependent templates),
    binned_bins for the binned likelihood.
    """

    def __init__(self, template: LCTemplate, phases, weights=None,
                 log10_ens=None, binned_bins: int = 1000):
        self.template = template
        self.phases = np.mod(np.asarray(phases, float), 1.0)
        self.weights = None if weights is None else np.asarray(weights, float)
        self.log10_ens = None if log10_ens is None else np.asarray(log10_ens, float)
        self.binned_bins = binned_bins
        self.ll: float | None = None

    # --- likelihoods ----------------------------------------------------------

    def _nll_fn(self, tmap: _Thetamap, binned: bool = False):
        import jax
        import jax.numpy as jnp

        if binned and self.log10_ens is not None:
            # per-photon energies do not survive collapsing onto phase-bin
            # centers (the reference bins energy separately, binned_ebins);
            # evaluate unbinned instead of silently dropping the energies
            binned = False
        if binned:
            # photons collapse onto weighted bin centers; each photon keeps
            # its own weight, gathering its bin's template value (the same
            # statistic as the reference's slice loop, as one gather)
            nb = self.binned_bins
            idx = np.minimum((self.phases * nb).astype(int), nb - 1)
            w = np.ones_like(self.phases) if self.weights is None else self.weights
            wsum = np.zeros(nb)
            wp = np.zeros(nb)
            np.add.at(wsum, idx, w)
            np.add.at(wp, idx, w * self.phases)
            occupied = wsum > 0
            centers = np.where(occupied, wp / np.where(occupied, wsum, 1.0), 0.0)
            x_eval = jnp.asarray(centers)
            gather = jnp.asarray(idx)
        else:
            x_eval = jnp.asarray(self.phases)
            gather = None
        wts = None if self.weights is None else jnp.asarray(self.weights)
        ens = (None if self.log10_ens is None
               else jnp.asarray(np.broadcast_to(self.log10_ens, self.phases.shape)))

        def nll(theta):
            f = tmap.density(theta, x_eval, log10_ens=ens)
            if gather is not None:
                f = f[gather]
            if wts is None:
                arg = jnp.maximum(f, 1e-300)
            else:
                arg = jnp.maximum(1.0 + wts * (f - 1.0), 1e-300)
            return -jnp.sum(jnp.log(arg))

        return jax.jit(nll), jax.jit(jax.grad(nll))

    def unbinned_loglikelihood(self, theta=None) -> float:
        tmap = _Thetamap(self.template)
        th = tmap.theta0() if theta is None else np.asarray(theta)
        nll, _ = self._nll_fn(tmap, binned=False)
        import jax.numpy as jnp

        return -float(nll(jnp.asarray(th)))

    def binned_loglikelihood(self, theta=None) -> float:
        tmap = _Thetamap(self.template)
        th = tmap.theta0() if theta is None else np.asarray(theta)
        nll, _ = self._nll_fn(tmap, binned=True)
        import jax.numpy as jnp

        return -float(nll(jnp.asarray(th)))

    def loglikelihood(self, theta=None) -> float:
        return self.unbinned_loglikelihood(theta)

    # --- fitting --------------------------------------------------------------

    def fit(self, unbinned: bool = True, use_gradient: bool = True,
            estimate_errors: bool = True, prior=None,
            overall_position_first: bool = False, quiet: bool = True,
            fit_shape: bool = True, fit_norms: bool = True,
            ftol: float = 1e-8) -> bool:
        """ML fit of all template parameters (reference LCFitter.fit).
        Modifies self.template in place; returns True on improvement.
        `prior` is an optional callable theta_phys -> -log prior
        (e.g. template.GaussianPrior)."""
        import jax.numpy as jnp
        from scipy.optimize import minimize

        if overall_position_first:
            dphi, _, _ = self.fit_position(unbinned=unbinned)
            self.template.rotate(dphi)

        tmap = _Thetamap(self.template, fit_shape=fit_shape, fit_norms=fit_norms)
        nll, gnll = self._nll_fn(tmap, binned=not unbinned)
        if prior is not None:
            import jax

            base = nll

            def nll_p(theta):
                return base(theta) + prior(tmap.physical(theta))

            nll = jax.jit(nll_p)
            gnll = jax.jit(jax.grad(nll_p))

        theta0 = tmap.theta0()
        ll0 = -float(nll(jnp.asarray(theta0)))
        res = minimize(
            lambda t: float(nll(jnp.asarray(t))),
            theta0,
            jac=(lambda t: np.asarray(gnll(jnp.asarray(t)))) if use_gradient else None,
            method="L-BFGS-B" if use_gradient else "Nelder-Mead",
            options={"ftol": ftol} if use_gradient else {},
        )
        ll1 = -float(res.fun)
        if not np.isfinite(ll1) or ll1 < ll0:
            if not quiet:
                print("Failed likelihood fit -- resetting parameters.")
            self.ll = ll0
            return False
        self._last_binned = not unbinned
        errors = self.hess_errors(tmap, np.asarray(res.x)) if estimate_errors else None
        tmap.write_back(np.asarray(res.x), errors)
        self.ll = ll1
        self._last_tmap = tmap
        self._last_theta = np.asarray(res.x)
        if not quiet:
            print(f"Improved log likelihood by {ll1 - ll0:.2f}")
        return True

    def hess_errors(self, tmap=None, theta=None) -> np.ndarray | None:
        """Physical-unit 1-sigma errors from the inverse Hessian of the NLL
        at the fit point, propagated through the transform jacobian
        (reference LCFitter.hess_errors)."""
        import jax
        import jax.numpy as jnp

        if tmap is None:
            tmap = getattr(self, "_last_tmap", None)
            theta = getattr(self, "_last_theta", None)
            if tmap is None:
                return None
        # curvature of the SAME objective the fit minimized: a binned fit's
        # optimum is not stationary for the unbinned NLL
        nll, _ = self._nll_fn(tmap, binned=getattr(self, "_last_binned", False))
        th = jnp.asarray(theta)
        try:
            H = np.asarray(jax.hessian(nll)(th))
            # spectral pseudo-inverse: same PSD-by-construction guarantee
            # as fitting.gls.gls_solve
            s, V = np.linalg.eigh((H + H.T) / 2.0)
            s_inv = np.where(s > 1e-12 * max(s[-1], 1e-300), 1.0 / np.where(s > 0, s, 1.0), 0.0)
            cov = (V * s_inv) @ V.T
            J = np.asarray(jax.jacobian(tmap.physical)(th))
            return np.sqrt(np.maximum(np.diag(J @ cov @ J.T), 0.0))
        except Exception:  # jaxlint: disable=silent-except — hessian errors fall back to None uncertainties, surfaced to the caller
            return None

    def bootstrap_errors(self, n: int = 50, rng=None) -> np.ndarray:
        """Physical-unit errors from refitting bootstrap resamples of the
        photons (reference LCFitter.bootstrap_errors)."""
        rng = rng or np.random.default_rng()
        base = self.template.copy()
        vals = []
        nph = len(self.phases)
        for _ in range(n):
            sel = rng.integers(0, nph, nph)
            f = LCFitter(
                base.copy(), self.phases[sel],
                None if self.weights is None else self.weights[sel],
                log10_ens=None if self.log10_ens is None
                else np.broadcast_to(self.log10_ens, (nph,))[sel],
                binned_bins=self.binned_bins,
            )
            if f.fit(estimate_errors=False, quiet=True):
                t = f.template
                vals.append(np.concatenate([
                    [c.phase for c in t.components],
                    np.concatenate([np.asarray(c.shape_values(), float)
                                    for c in t.components])
                    if any(c.shape_names for c in t.components) else np.zeros(0),
                    [c.ampl for c in t.components],
                ]))
        return np.std(np.asarray(vals), axis=0) if vals else None

    def fit_position(self, unbinned: bool = True, track: bool = False,
                     n_grid: int = 256):
        """Overall phase shift of the template vs the data + error
        (reference LCFitter.fit_position). `track` restricts the search to
        +-0.2 cycles around zero shift (avoids the half-cycle ambiguity of
        two-peaked profiles); err and lnlike always describe the returned
        peak."""
        window = (-0.2, 0.2) if track else None
        return fit_phase_shift(
            self.template, self.phases, self.weights, n_grid=n_grid,
            window=window,
        )

    def remove_weak(self, min_ampl: float = 0.005) -> int:
        """Drop components whose amplitude fell below `min_ampl`
        (their norm returns to the background). Returns how many."""
        weak = [i for i, c in enumerate(self.template.components)
                if c.ampl < min_ampl]
        for i in reversed(weak):
            self.template.delete_primitive(i)
        return len(weak)

    # --- reporting ------------------------------------------------------------

    def __str__(self) -> str:
        head = f"\nLog Likelihood for fit: {self.ll:.2f}\n" if self.ll is not None else ""
        return head + str(self.template)

    def write_template(self, path: str) -> None:
        self.template.write(path)

    def plot(self, nbins: int = 50, ax=None):
        """Weighted light curve + fitted template overlay."""
        import matplotlib.pyplot as plt

        if ax is None:
            _, ax = plt.subplots()
        edges, counts, errs = weighted_light_curve(
            nbins, self.phases, self.weights, normed=True
        )
        x = 0.5 * (edges[1:] + edges[:-1])
        ax.errorbar(x, counts, yerr=errs, fmt="o", ms=3, label="data")
        grid = np.linspace(0, 1, 512)
        ax.plot(grid, self.template(grid), label="template")
        ax.set_xlabel("phase")
        ax.set_ylabel("normalized rate")
        ax.legend()
        return ax


# --- legacy one-call fit (original pint_tpu surface) --------------------------


def fit_template(template: LCTemplate, phases, weights=None,
                 fit_shape: bool = True):
    """Unbinned weighted ML fit of the template's parameters; returns
    (fitted LCTemplate, {param: err}, lnlike). Kept from the original
    module: now a thin wrapper over LCFitter supporting every primitive
    type (not just Gaussians)."""
    t = template.copy()
    f = LCFitter(t, phases, weights)
    f.fit(fit_shape=fit_shape, fit_norms=fit_shape, quiet=True)
    errs: dict[str, float] = {}
    for k, c in enumerate(t.components, start=1):
        for name, val in getattr(c, "fit_errors", {}).items():
            errs[f"{name}{k}"] = val
    return t, errs, f.ll
