"""Normalization simplex for pulse-profile templates.

Reference: pint/templates/lcnorm.py NormAngles (500 LoC) and
lcenorm.py ENormAngles. Component amplitudes n_1..n_k with
sum(n) <= 1 (the remainder is the unpulsed background) are encoded as k
angles, so ANY unconstrained angle vector maps to a valid point of the
simplex — the fitters can optimize freely with no barrier terms:

    total = sin^2(t_0)                    (so 1 - sum(n) = cos^2(t_0))
    the k-1 remaining angles stick-break the total among components:
        g_1 = cos^2(t_1)
        g_2 = sin^2(t_1) cos^2(t_2)
        ...
        g_k = sin^2(t_1) ... sin^2(t_{k-1})
    n_i = total * g_i

The invariant 1 - sum(n) = cos^2(t_0) matches the reference's convention
(its test_norms asserts exactly that). All derivatives of the map come
from jax autodiff; `norms_from_angles_jnp` is the jit-compatible form the
fitters compose into the likelihood.
"""

from __future__ import annotations

import numpy as np


def norms_from_angles_jnp(t):
    """Angles (k,) -> norms (k,) in jax-compatible form (see module doc).
    Used INSIDE jitted fit likelihoods; host-side bookkeeping uses the
    numpy twin `norms_from_angles` (on TPU backends device trig is only
    f32-accurate, far below what parameter round-trips need)."""
    import jax.numpy as jnp

    total = jnp.sin(t[0]) ** 2
    if t.shape[0] == 1:
        return total[None] if total.ndim == 0 else jnp.asarray([total])
    s2 = jnp.sin(t[1:]) ** 2
    c2 = jnp.cos(t[1:]) ** 2
    # prefix products of sin^2: prod_{j<i} s2_j
    prefix = jnp.concatenate([jnp.ones(1), jnp.cumprod(s2)])
    g = prefix[:-1] * c2  # g_1 .. g_{k-1}
    g = jnp.concatenate([g, prefix[-1:]])  # g_k = full product
    return total * g


def norms_from_angles(t: np.ndarray) -> np.ndarray:
    """Numpy twin of `norms_from_angles_jnp` (exact f64 on the host)."""
    t = np.asarray(t, float)
    total = np.sin(t[0]) ** 2
    if t.size == 1:
        return np.array([total])
    s2 = np.sin(t[1:]) ** 2
    c2 = np.cos(t[1:]) ** 2
    prefix = np.concatenate([[1.0], np.cumprod(s2)])
    g = np.concatenate([prefix[:-1] * c2, prefix[-1:]])
    return total * g


def angles_from_norms(n: np.ndarray) -> np.ndarray:
    """Inverse map: norms (k,) with sum <= 1 -> angles (k,)."""
    n = np.asarray(n, float)
    total = n.sum()
    if total > 1.0 + 1e-9:
        raise ValueError(f"norms sum to {total} > 1")
    k = n.size
    t = np.empty(k)
    t[0] = np.arcsin(np.sqrt(np.clip(total, 0.0, 1.0)))
    rem = total
    for i in range(k - 1):
        # g_i fraction of remaining: cos^2(t_{i+1}) = n_i / rem
        frac = n[i] / rem if rem > 0 else 1.0
        t[i + 1] = np.arccos(np.sqrt(np.clip(frac, 0.0, 1.0)))
        rem -= n[i]
    return t


class NormAngles:
    """Mutable amplitude-simplex object (reference lcnorm.NormAngles:19).

    `p` holds the angles; calling the object returns the norms. `free`
    masks which angles the fitters may vary.
    """

    name = "NormAngles"

    def __init__(self, norms, free=None):
        norms = np.asarray(norms, float)
        self.p = angles_from_norms(norms)
        self.free = (
            np.ones(self.p.size, bool) if free is None else np.asarray(free, bool)
        )
        self.errors = np.zeros_like(self.p)

    def __call__(self, log10_ens=None) -> np.ndarray:
        return norms_from_angles(self.p)

    def __len__(self) -> int:
        return self.p.size

    def num_parameters(self, free: bool = True) -> int:
        return int(self.free.sum()) if free else self.p.size

    def get_parameters(self, free: bool = True) -> np.ndarray:
        return self.p[self.free] if free else self.p.copy()

    def set_parameters(self, q, free: bool = True) -> bool:
        q = np.asarray(q, float)
        if free:
            self.p[self.free] = q
        else:
            self.p[:] = q
        return True

    def set_single_norm(self, index: int, value: float) -> None:
        """Set one component's norm, preserving the others (re-encodes the
        angle vector; raises if the new vector leaves the simplex)."""
        n = np.array(self())
        n[index] = value
        self.p[:] = angles_from_norms(n)

    def norm_ok(self) -> bool:
        n = self()
        return bool(np.all(n >= 0) and n.sum() <= 1.0 + 1e-9)

    def sanity_checks(self) -> bool:
        return self.norm_ok()

    def copy(self) -> "NormAngles":
        out = NormAngles(self())
        out.p = self.p.copy()
        out.free = self.free.copy()
        out.errors = self.errors.copy()
        return out

    def gradient(self, log10_ens=None, free: bool = True) -> np.ndarray:
        """(k, n_param) d norms / d angles via autodiff."""
        import jax
        import jax.numpy as jnp

        J = np.asarray(jax.jacobian(norms_from_angles_jnp)(jnp.asarray(self.p)))
        return J[:, self.free] if free else J


class ENormAngles(NormAngles):
    """Energy-dependent norms (reference lcenorm.ENormAngles:12): the
    ANGLES move linearly in log10(E/MeV) around the pivot 3, so the
    simplex constraint holds automatically at every energy:
        t(e) = t + slope * (e - 3);  n(e) = norms(t(e)).
    """

    name = "ENormAngles"

    def __init__(self, norms, slope=None, free=None, slope_free=None):
        super().__init__(norms, free=free)
        self.slope = (
            np.zeros_like(self.p) if slope is None else np.asarray(slope, float)
        )
        self.slope_free = (
            np.zeros(self.p.size, bool)
            if slope_free is None
            else np.asarray(slope_free, bool)
        )

    def __call__(self, log10_ens=None) -> np.ndarray:
        if log10_ens is None:
            return super().__call__()
        e = np.atleast_1d(np.asarray(log10_ens, float))
        t = self.p[:, None] + self.slope[:, None] * (e[None, :] - 3.0)
        out = np.stack(
            [norms_from_angles(t[:, i]) for i in range(e.size)], axis=1
        )
        return out  # (k, n_e)

    def num_parameters(self, free: bool = True) -> int:
        base = super().num_parameters(free)
        return base + (int(self.slope_free.sum()) if free else self.slope.size)

    def get_parameters(self, free: bool = True) -> np.ndarray:
        if free:
            return np.concatenate([self.p[self.free], self.slope[self.slope_free]])
        return np.concatenate([self.p, self.slope])

    def set_parameters(self, q, free: bool = True) -> bool:
        q = np.asarray(q, float)
        if free:
            na = int(self.free.sum())
            self.p[self.free] = q[:na]
            self.slope[self.slope_free] = q[na:]
        else:
            self.p[:] = q[: self.p.size]
            self.slope[:] = q[self.p.size :]
        return True
