"""Energy-dependent pulse-profile primitives.

Reference: pint/templates/lceprimitives.py (LCEGaussian etc.) and
lcenorm.py. Fermi-LAT pulse shapes drift with photon energy; the
reference models every primitive parameter as linear in
log10(E/MeV) about the pivot energy 10^3 MeV:

    p(e) = p + slope * (e - 3)

Here that rule is one mixin: an energy-dependent primitive wraps its base
class's `density_jnp` with shifted parameters, so the same autodiff
machinery fits slopes with no extra derivative code. `density_e` is the
host-side evaluation the LCTemplate.__call__ dispatches to when
`log10_ens` is given.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from pint_tpu.templates.primitives import (
    LCGaussian,
    LCGaussian2,
    LCLorentzian,
    LCLorentzian2,
    LCSkewGaussian,
    LCVonMises,
)

__all__ = [
    "LCEGaussian",
    "LCEGaussian2",
    "LCELorentzian",
    "LCELorentzian2",
    "LCESkewGaussian",
    "LCEVonMises",
]

PIVOT = 3.0  # log10(MeV)


class _EDepMixin:
    """Adds linear-in-log10(E) drift to (phase, *shape) of the base
    primitive. `slope` has one entry per (phase + shape) parameter."""

    def _slopes(self) -> np.ndarray:
        n = 1 + len(self.shape_names)
        s = np.asarray(self.slope, float)
        if s.size != n:
            raise ValueError(f"slope must have {n} entries (phase + shapes)")
        return s

    def params_at(self, log10_en):
        """(phase, *shapes) at the given energy."""
        s = self._slopes()
        de = np.asarray(log10_en, float) - PIVOT
        vals = [self.phase + s[0] * de]
        for i, n in enumerate(self.shape_names):
            vals.append(getattr(self, n) + s[1 + i] * de)
        return vals

    def density_e(self, x, log10_ens) -> np.ndarray:
        """Host-side density at per-photon energies (vector or scalar)."""
        x = np.asarray(x, float)
        e = np.asarray(log10_ens, float)
        if e.ndim == 0:
            p = self.params_at(float(e))
            return np.asarray(self.density_jnp(x, *p))
        return np.asarray(self.density_jnp_e(x, np.broadcast_to(e, x.shape)))

    def density_jnp_e(self, x, log10_ens):
        """jax-compatible density with per-photon energies — the form the
        fitters jit. Slopes enter as fixed data here; use
        `density_jnp_e_theta` to expose them to autodiff."""
        import jax.numpy as jnp

        s = self._slopes()
        de = jnp.asarray(log10_ens) - PIVOT
        phase = self.phase + s[0] * de
        shapes = [getattr(self, n) + s[1 + i] * de
                  for i, n in enumerate(self.shape_names)]
        return self.density_jnp(x, phase, *shapes)

    @classmethod
    def density_jnp_e_theta(cls, x, log10_ens, phase, shapes, slopes):
        """Fully-parameterized energy-dependent density for fitting:
        `shapes` and `slopes` are sequences (slopes: phase first)."""
        de = log10_ens - PIVOT
        ph = phase + slopes[0] * de
        sh = [s + slopes[1 + i] * de for i, s in enumerate(shapes)]
        return cls.density_jnp(x, ph, *sh)

    def is_energy_dependent(self) -> bool:
        return True


def _edep(name, base):
    """Build the energy-dependent dataclass for a base primitive."""

    @dataclass
    class _E(_EDepMixin, base):
        slope: np.ndarray = field(default=None)

        def __post_init__(self):
            if self.slope is None:
                self.slope = np.zeros(1 + len(self.shape_names))
            else:
                self.slope = np.asarray(self.slope, float)

    _E.__name__ = name
    _E.__qualname__ = name
    _E.__doc__ = (
        f"Energy-dependent {base.__name__} (linear-in-log10E parameters; "
        f"reference lceprimitives.{name})."
    )
    return _E


LCEGaussian = _edep("LCEGaussian", LCGaussian)
LCEGaussian2 = _edep("LCEGaussian2", LCGaussian2)
LCELorentzian = _edep("LCELorentzian", LCLorentzian)
LCELorentzian2 = _edep("LCELorentzian2", LCLorentzian2)
LCESkewGaussian = _edep("LCESkewGaussian", LCSkewGaussian)
LCEVonMises = _edep("LCEVonMises", LCVonMises)
