"""Pulse-profile primitive components.

Reference: pint/templates/lcprimitives.py (1,691 LoC). The reference pairs
every primitive with hand-written analytic gradients/hessians; here each
primitive instead defines ONE pure density function in jax-compatible form
(`density_jnp`), and every derivative the fitters need comes from autodiff
— the tpu-native replacement for the whole hand-derivative layer.

Conventions (shared with the original pint_tpu templates module, kept for
compatibility with event_optimize and the photonphase tools):

- each component carries its own integral amplitude `ampl` (the reference
  separates amplitudes into NormAngles; pint_tpu.templates.norms provides
  the same simplex object for direct manipulation);
- `phase` is the component location in cycles; `fwhm` the full width at
  half maximum in cycles (two-sided primitives carry fwhm1/fwhm2);
- `density(x)` returns the UNIT-normalized component density (integral 1
  over one cycle); the template multiplies by `ampl` and adds the uniform
  background.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

FWHM_TO_SIGMA = 1.0 / (2.0 * np.sqrt(2.0 * np.log(2.0)))
_WRAPS = 3


def _jnp():
    import jax.numpy as jnp

    return jnp


class LCPrimitive:
    """Base: a unit-normalized periodic density with (phase, width(s), ampl).

    Subclasses define `shape_names` (parameter names besides phase/ampl)
    and the static `density_jnp(x, phase, *shape)` in jax-compatible form;
    `density` is the host (numpy) wrapper. Everything else — gradients,
    hessians, fitting — is autodiff downstream.
    """

    shape_names: tuple = ("fwhm",)
    #: bounds per shape parameter (used by the fitters' unconstrained maps)
    shape_bounds: tuple = ((0.005, 0.5),)

    # dataclass subclasses set: phase, ampl + the shape params by name
    def shape_values(self) -> tuple:
        return tuple(getattr(self, n) for n in self.shape_names)

    def density(self, x: np.ndarray) -> np.ndarray:
        vals = self.density_jnp(np.asarray(x, float), self.phase, *self.shape_values())
        return np.asarray(vals)

    def __call__(self, x: np.ndarray) -> np.ndarray:
        return self.density(x)

    def integrate(self, x1: float = 0.0, x2: float = 1.0) -> float:
        """Integral of the unit density over [x1, x2] (numeric; cheap and
        exact enough for component bookkeeping — the wrapped closed forms
        make the full-cycle integral exactly 1)."""
        from scipy.integrate import quad

        return quad(lambda ph: float(self.density(np.array([ph]))[0]), x1, x2)[0]

    def hwhm(self, right: bool = False) -> float:
        """Half-width at half max (numeric on the density)."""
        import scipy.optimize as so

        peak = float(self.density(np.array([self.phase]))[0])

        def f(d):
            return float(self.density(np.array([self.phase + (d if right else -d)]))[0]) - 0.5 * peak

        try:
            return so.brentq(f, 1e-6, 0.5)
        except ValueError:
            return 0.25

    def get_location(self) -> float:
        return self.phase

    def is_two_sided(self) -> bool:
        return False

    def copy(self):
        return replace(self)


@dataclass
class LCGaussian(LCPrimitive):
    """Wrapped Gaussian (reference lcprimitives.LCGaussian:714)."""

    phase: float
    fwhm: float
    ampl: float

    shape_names = ("fwhm",)
    shape_bounds = ((0.005, 0.5),)

    @staticmethod
    def density_jnp(x, phase, fwhm):
        jnp = _jnp()
        s = fwhm * FWHM_TO_SIGMA
        out = jnp.zeros_like(x)
        for k in range(-_WRAPS, _WRAPS + 1):
            out = out + jnp.exp(-0.5 * ((x - phase + k) / s) ** 2)
        return out / (s * np.sqrt(2 * np.pi))


@dataclass
class LCGaussian2(LCPrimitive):
    """Two-sided wrapped Gaussian: independent left/right widths joined at
    the mode (reference lcprimitives.LCGaussian2:787). Unit-normalized:
    each half is half a Gaussian of its own sigma, weighted so the density
    is continuous at the peak."""

    phase: float
    fwhm1: float
    fwhm2: float
    ampl: float

    shape_names = ("fwhm1", "fwhm2")
    shape_bounds = ((0.005, 0.5), (0.005, 0.5))

    def is_two_sided(self) -> bool:
        return True

    @staticmethod
    def density_jnp(x, phase, fwhm1, fwhm2):
        jnp = _jnp()
        s1 = fwhm1 * FWHM_TO_SIGMA
        s2 = fwhm2 * FWHM_TO_SIGMA
        # continuous at the mode, total integral 1:
        # f(x) = 2/(s1+s2) * [ phi((x-mu)/s1) left, phi((x-mu)/s2) right ]
        norm = 2.0 / (s1 + s2) / np.sqrt(2 * np.pi)
        out = jnp.zeros_like(x)
        for k in range(-_WRAPS, _WRAPS + 1):
            d = x - phase + k
            s = jnp.where(d < 0, s1, s2)
            out = out + jnp.exp(-0.5 * (d / s) ** 2)
        return norm * out


@dataclass
class LCSkewGaussian(LCPrimitive):
    """Wrapped skew-normal (reference lcprimitives.LCSkewGaussian:851):
    density 2 phi(z) Phi(shape * z), z = (x - mu)/sigma."""

    phase: float
    fwhm: float
    shape: float
    ampl: float

    shape_names = ("fwhm", "shape")
    shape_bounds = ((0.005, 0.5), (-20.0, 20.0))

    def is_two_sided(self) -> bool:
        return True

    @staticmethod
    def density_jnp(x, phase, fwhm, shape):
        jnp = _jnp()
        from jax.scipy.special import ndtr

        s = fwhm * FWHM_TO_SIGMA
        out = jnp.zeros_like(x)
        for k in range(-_WRAPS, _WRAPS + 1):
            z = (x - phase + k) / s
            out = out + jnp.exp(-0.5 * z * z) * ndtr(shape * z)
        return 2.0 * out / (s * np.sqrt(2 * np.pi))


@dataclass
class LCLorentzian(LCPrimitive):
    """Wrapped Lorentzian (Cauchy); the sum over all cycles has the closed
    form sinh(g) / (cosh(g) - cos(2 pi (x - mu))) with g = 2 pi * HWHM
    (reference lcprimitives.LCLorentzian:994)."""

    phase: float
    fwhm: float
    ampl: float

    shape_names = ("fwhm",)
    shape_bounds = ((0.005, 0.5),)

    @staticmethod
    def density_jnp(x, phase, fwhm):
        jnp = _jnp()
        g = 2.0 * np.pi * (fwhm / 2.0)
        return jnp.sinh(g) / (jnp.cosh(g) - jnp.cos(2.0 * np.pi * (x - phase)))


@dataclass
class LCLorentzian2(LCPrimitive):
    """Two-sided wrapped Lorentzian: left/right HWHM joined at the mode
    (reference lcprimitives.LCLorentzian2:1079)."""

    phase: float
    fwhm1: float
    fwhm2: float
    ampl: float

    shape_names = ("fwhm1", "fwhm2")
    shape_bounds = ((0.005, 0.5), (0.005, 0.5))

    def is_two_sided(self) -> bool:
        return True

    @staticmethod
    def density_jnp(x, phase, fwhm1, fwhm2):
        jnp = _jnp()
        # continuous at the peak, unit integral: f(d) = A / (1 + (d/h)^2)
        # per side with A = 2 / (pi (h1 + h2)); wrapped numerically, with
        # the finite-wrap tail mass (Lorentzian tails are heavy) folded
        # back into the normalization so the cycle integral stays 1
        h1 = fwhm1 / 2.0
        h2 = fwhm2 / 2.0
        norm = 2.0 / (np.pi * (h1 + h2))
        out = jnp.zeros_like(x)
        for k in range(-_WRAPS, _WRAPS + 1):
            d = x - phase + k
            h = jnp.where(d < 0, h1, h2)
            out = out + 1.0 / (1.0 + (d / h) ** 2)
        edge = _WRAPS + 0.5
        lost = norm * (
            h1 * (np.pi / 2.0 - jnp.arctan(edge / h1))
            + h2 * (np.pi / 2.0 - jnp.arctan(edge / h2))
        )
        return norm * out / (1.0 - lost)


@dataclass
class LCVonMises(LCPrimitive):
    """Von Mises component, exactly periodic and normalized on [0, 1)
    (reference lcprimitives.LCVonMises:1168); fwhm maps to the
    concentration via cos(pi*fwhm) = 1 - log(2)/kappa."""

    phase: float
    fwhm: float
    ampl: float

    shape_names = ("fwhm",)
    shape_bounds = ((0.005, 0.9),)

    @staticmethod
    def density_jnp(x, phase, fwhm):
        jnp = _jnp()
        from jax.scipy.special import i0e

        kappa = np.log(2.0) / (1.0 - jnp.cos(np.pi * fwhm))
        # i0e = exp(-|k|) I0(k): exp(k cos - k) / i0e(k) is overflow-safe
        return jnp.exp(kappa * (jnp.cos(2 * np.pi * (x - phase)) - 1.0)) / i0e(kappa)


@dataclass
class LCKing(LCPrimitive):
    """Wrapped King-function profile (reference lcprimitives.LCKing:1243):
    f(r) ~ (1 + r^2/(2 gamma sigma^2))^(-gamma), the PSF-like heavy-tail
    shape; sigma from fwhm, gamma the tail index."""

    phase: float
    fwhm: float
    gamma: float
    ampl: float

    shape_names = ("fwhm", "gamma")
    shape_bounds = ((0.005, 0.5), (1.05, 20.0))

    @staticmethod
    def density_jnp(x, phase, fwhm, gamma):
        jnp = _jnp()
        s = fwhm * FWHM_TO_SIGMA
        out = jnp.zeros_like(x)
        for k in range(-_WRAPS, _WRAPS + 1):
            d = x - phase + k
            out = out + (1.0 + d * d / (2.0 * gamma * s * s)) ** (-gamma)
        # normalize numerically on the wrap window: closed-form King
        # integral over (-inf, inf) = s sqrt(2 gamma) B(1/2, gamma - 1/2)
        from jax.scipy.special import gammaln

        lgnorm = (
            0.5 * jnp.log(2.0 * gamma)
            + gammaln(0.5)
            + gammaln(gamma - 0.5)
            - gammaln(gamma)
        )
        return out / (s * jnp.exp(lgnorm))


@dataclass
class LCTopHat(LCPrimitive):
    """Periodic top-hat of width `width` cycles (reference
    lcprimitives.LCTopHat:1301). The edges are smoothed over ~1e-3 cycles
    so the density stays autodiff-friendly."""

    phase: float
    width: float
    ampl: float

    shape_names = ("width",)
    shape_bounds = ((0.01, 0.99),)

    @staticmethod
    def density_jnp(x, phase, width, _soft=1e-3):
        jnp = _jnp()
        # distance to the component center, wrapped to [-0.5, 0.5)
        d = jnp.mod(x - phase + 0.5, 1.0) - 0.5
        edge0 = -width / 2.0
        edge1 = width / 2.0
        val = jax_sigmoid((d - edge0) / _soft) * jax_sigmoid((edge1 - d) / _soft)
        return val / width

    def hwhm(self, right: bool = False) -> float:
        return self.width / 2.0


def jax_sigmoid(z):
    jnp = _jnp()
    return 1.0 / (1.0 + jnp.exp(-z))


@dataclass
class LCHarmonic(LCPrimitive):
    """Single sinusoidal harmonic of order n (reference
    lcprimitives.LCHarmonic:1329): f(x) = 1 + cos(2 pi n (x - phase)),
    unit mean over the cycle (its `ampl` is the modulation fraction)."""

    phase: float
    order: int
    ampl: float

    shape_names = ()
    shape_bounds = ()

    # instance method (not static like the analytic shapes): `order` is
    # structural data, never a fit parameter, so it must ride the instance
    # — a default-argument form would silently evaluate order=1 in fits
    def density_jnp(self, x, phase=None, *shape):
        jnp = _jnp()
        ph = self.phase if phase is None else phase
        return 1.0 + jnp.cos(2 * np.pi * self.order * (x - ph))

    def density(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.density_jnp(np.asarray(x, float)))

    def shape_values(self) -> tuple:
        return ()


@dataclass
class LCKernelDensity(LCPrimitive):
    """Non-parametric wrapped-KDE profile from a photon sample (reference
    lcprimitives.LCKernelDensity:1449). Built once from data; served from a
    dense grid by linear interpolation; no free shape parameters."""

    phase: float = 0.0
    ampl: float = 1.0
    bw: float = 0.01
    grid: np.ndarray = field(default=None, repr=False)
    vals: np.ndarray = field(default=None, repr=False)

    shape_names = ()
    shape_bounds = ()

    @classmethod
    def from_phases(cls, phases, weights=None, bw: float | None = None,
                    ngrid: int = 512) -> "LCKernelDensity":
        ph = np.mod(np.asarray(phases, float), 1.0)
        w = np.ones_like(ph) if weights is None else np.asarray(weights, float)
        if bw is None:
            # Silverman on the circular std, floored for sparse data
            neff = w.sum() ** 2 / (w**2).sum()
            z = np.exp(2j * np.pi * ph)
            R = abs(np.sum(w * z) / w.sum())
            circ_std = np.sqrt(-2 * np.log(max(R, 1e-12))) / (2 * np.pi)
            bw = max(1.06 * circ_std * neff ** (-0.2), 2e-3)
        grid = np.linspace(0, 1, ngrid, endpoint=False)
        d = grid[:, None] - ph[None, :]
        d = np.mod(d + 0.5, 1.0) - 0.5
        vals = (w[None, :] * np.exp(-0.5 * (d / bw) ** 2)).sum(axis=1)
        vals /= vals.mean()  # unit integral on the cycle
        return cls(phase=0.0, ampl=1.0, bw=bw, grid=grid, vals=vals)

    def density(self, x: np.ndarray) -> np.ndarray:
        xx = np.mod(np.asarray(x, float) - self.phase, 1.0)
        return np.interp(xx, np.append(self.grid, 1.0), np.append(self.vals, self.vals[0]))

    def density_jnp(self, x, phase=None, *shape):
        jnp = _jnp()
        xx = jnp.mod(x - (self.phase if phase is None else phase), 1.0)
        g = jnp.asarray(np.append(self.grid, 1.0))
        v = jnp.asarray(np.append(self.vals, self.vals[0]))
        return jnp.interp(xx, g, v)

    def shape_values(self) -> tuple:
        return ()


@dataclass
class LCEmpiricalFourier(LCPrimitive):
    """Truncated Fourier-series profile fit to a photon sample (reference
    lcprimitives.LCEmpiricalFourier:1354): f(x) = 1 + 2 sum_k [a_k cos +
    b_k sin](2 pi k x); exactly unit-normalized. `phase` rotates the
    series; harmonics are fixed data, not fit parameters."""

    phase: float = 0.0
    ampl: float = 1.0
    alphas: np.ndarray = field(default=None, repr=False)
    betas: np.ndarray = field(default=None, repr=False)
    clip_norm: float = 1.0

    shape_names = ()
    shape_bounds = ()

    @classmethod
    def from_phases(cls, phases, weights=None, nharm: int = 20) -> "LCEmpiricalFourier":
        ph = np.mod(np.asarray(phases, float), 1.0)
        w = np.ones_like(ph) if weights is None else np.asarray(weights, float)
        W = w.sum()
        ks = np.arange(1, nharm + 1)
        alphas = (w[None, :] * np.cos(2 * np.pi * ks[:, None] * ph[None, :])).sum(1) / W
        betas = (w[None, :] * np.sin(2 * np.pi * ks[:, None] * ph[None, :])).sum(1) / W
        out = cls(phase=0.0, ampl=1.0, alphas=alphas, betas=betas)
        # the truncated series rings negative around sharp peaks and the
        # positivity clip adds mass; fold the clipped integral back into
        # the normalization (rotation-invariant, so computed once here)
        grid = np.linspace(0, 1, 4096, endpoint=False)
        out.clip_norm = float(np.mean(out.density(grid)))
        return out

    def density(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.density_jnp(np.asarray(x, float)))

    def density_jnp(self, x, phase=None, *shape):
        jnp = _jnp()
        ph = self.phase if phase is None else phase
        ks = np.arange(1, len(self.alphas) + 1)
        ang = 2 * np.pi * ks[None, :] * (jnp.asarray(x)[..., None] - ph)
        out = 1.0 + 2.0 * jnp.sum(
            jnp.asarray(self.alphas) * jnp.cos(ang)
            + jnp.asarray(self.betas) * jnp.sin(ang),
            axis=-1,
        )
        return jnp.maximum(out, 1e-12) / self.clip_norm

    def shape_values(self) -> tuple:
        return ()


def convert_primitive(p1: LCPrimitive, ptype=LCLorentzian) -> LCPrimitive:
    """Convert a primitive to a different family preserving location, HWHM
    and amplitude (reference lcprimitives.convert_primitive:1600)."""
    h = p1.hwhm()
    fwhm = 2.0 * h
    kw: dict = {"phase": p1.get_location(), "ampl": p1.ampl}
    if ptype in (LCGaussian, LCLorentzian, LCVonMises, LCSkewGaussian):
        kw["fwhm"] = fwhm
        if ptype is LCSkewGaussian:
            kw["shape"] = 0.0
    elif ptype in (LCGaussian2, LCLorentzian2):
        kw["fwhm1"] = 2.0 * p1.hwhm(right=False)
        kw["fwhm2"] = 2.0 * p1.hwhm(right=True)
    elif ptype is LCKing:
        kw["fwhm"] = fwhm
        kw["gamma"] = 3.0
    elif ptype is LCTopHat:
        kw["width"] = fwhm
    else:
        raise TypeError(f"cannot convert to {ptype.__name__}")
    return ptype(**kw)
