"""Polycos: piecewise polynomial pulse-phase predictors (TEMPO format).

Reference: pint/polycos.py (Polycos:677 — generate_polycos, TEMPO
polyco.dat read/write, phase/frequency evaluation). Convention (TEMPO):

    DT = (t - TMID) [minutes]
    phase(t) = RPHASE + 60 DT F0 + sum_i COEFF[i] DT^i
    f(t) [Hz] = F0 + (1/60) sum_i i COEFF[i] DT^(i-1)

Generation evaluates the full timing model's TZR-anchored absolute phase at
Chebyshev-spaced nodes per segment (one prepared-TOAs pipeline call for ALL
segments at once) and least-squares fits the residual polynomial.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from pint_tpu.residuals import Residuals
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.polycos")


@dataclass
class PolycoEntry:
    psr: str
    tmid_mjd: float
    rphase_int: int
    rphase_frac: float
    f0: float
    obs: str
    span_min: float
    coeffs: np.ndarray  # (ncoeff,)
    freq_mhz: float
    dm: float = 0.0

    def covers(self, mjd) -> np.ndarray:
        dt_min = (np.asarray(mjd) - self.tmid_mjd) * 1440.0
        return np.abs(dt_min) <= self.span_min / 2.0

    def phase(self, mjd) -> np.ndarray:
        """Absolute phase (turns, relative to the generation's reference)."""
        dt = (np.asarray(mjd, np.longdouble) - np.longdouble(self.tmid_mjd)) * 1440.0
        poly = np.polynomial.polynomial.polyval(
            np.asarray(dt, float), self.coeffs
        )
        return (
            np.longdouble(self.rphase_int)
            + np.longdouble(self.rphase_frac)
            + 60.0 * dt * np.longdouble(self.f0)
            + poly
        )

    def frequency(self, mjd) -> np.ndarray:
        """Apparent spin frequency [Hz]."""
        dt = (np.asarray(mjd, float) - self.tmid_mjd) * 1440.0
        dcoef = self.coeffs[1:] * np.arange(1, len(self.coeffs))
        return self.f0 + np.polynomial.polynomial.polyval(dt, dcoef) / 60.0


@dataclass
class Polycos:
    entries: list[PolycoEntry] = field(default_factory=list)

    def find_entry(self, mjd: float) -> PolycoEntry:
        best, best_dt = None, np.inf
        for e in self.entries:
            dt = abs(mjd - e.tmid_mjd) * 1440.0
            if dt <= e.span_min / 2.0 + 1e-6 and dt < best_dt:
                best, best_dt = e, dt
        if best is None:
            raise ValueError(f"no polyco entry covers MJD {mjd}")
        return best

    def eval_abs_phase(self, mjd) -> np.ndarray:
        mjd = np.atleast_1d(np.asarray(mjd, float))
        return np.array([self.find_entry(m).phase(m) for m in mjd])

    def eval_spin_freq(self, mjd) -> np.ndarray:
        mjd = np.atleast_1d(np.asarray(mjd, float))
        return np.array([self.find_entry(m).frequency(m) for m in mjd])

    # --- generation ----------------------------------------------------------------

    @classmethod
    def generate_polycos(
        cls,
        model,
        mjd_start: float,
        mjd_end: float,
        obs: str = "geocenter",
        seg_length_min: float = 60.0,
        ncoeff: int = 12,
        obs_freq_mhz: float = 1400.0,
        nodes_per_seg: int | None = None,
    ) -> "Polycos":
        """Fit polyco segments to the full model (reference
        generate_polycos, polycos.py:677)."""
        from pint_tpu.astro import time as ptime
        from pint_tpu.toas import prepare_arrays

        nseg = max(1, int(np.ceil((mjd_end - mjd_start) * 1440.0 / seg_length_min)))
        nn = nodes_per_seg or max(2 * ncoeff, 24)
        seg_len_d = seg_length_min / 1440.0
        # Chebyshev-spaced nodes in every segment, one prep pipeline call
        k = np.arange(nn)
        cheb = np.cos(np.pi * (2 * k + 1) / (2 * nn))[::-1]  # (-1,1)
        tmids = mjd_start + (np.arange(nseg) + 0.5) * seg_len_d
        mjds = (tmids[:, None] + cheb[None, :] * seg_len_d / 2.0).ravel()
        utc = ptime.MJDEpoch.from_mjd_float(mjds)
        n = mjds.size
        toas = prepare_arrays(
            utc,
            np.full(n, 1.0),
            np.full(n, obs_freq_mhz),
            np.array([obs] * n),
            ephem=model.ephem or "auto",
            planets=bool(model.planet_shapiro),
        )
        r = Residuals(toas, model, subtract_mean=False, track_mode="nearest")
        # absolute (TZR-anchored) phase = integer pulse number + fractional
        pn = r.pulse_numbers
        frac = r.phase_resids
        from pint_tpu.models.base import leaf_to_f64

        f0 = float(np.asarray(leaf_to_f64(model.params["F0"])))
        dm = float(np.asarray(leaf_to_f64(model.params.get("DM", 0.0))))
        entries = []
        for s in range(nseg):
            sl = slice(s * nn, (s + 1) * nn)
            tmid = tmids[s]
            dt_min = (mjds[sl] - tmid) * 1440.0
            phase = np.asarray(pn[sl], np.longdouble) + np.asarray(frac[sl], np.longdouble)
            # reference phase at TMID: nearest integer of the node-mean trend
            base = phase - 60.0 * np.asarray(dt_min, np.longdouble) * np.longdouble(f0)
            rphase_int = int(np.floor(float(np.mean(base))))
            resid = np.asarray(base - rphase_int, float)
            # fit in u = dt/(span/2) in [-1,1] for conditioning, then
            # rescale to the TEMPO dt-minutes basis
            half = seg_length_min / 2.0
            V = np.vander(dt_min / half, ncoeff, increasing=True)
            cu, *_ = np.linalg.lstsq(V, resid, rcond=None)
            coeffs = cu / half ** np.arange(ncoeff)
            # fold the constant into RPHASE (TEMPO convention)
            rphase_frac = float(coeffs[0] % 1.0)
            rphase_int += int(np.floor(coeffs[0]))
            coeffs[0] = 0.0
            entries.append(
                PolycoEntry(
                    psr=model.psr_name or "PSR",
                    tmid_mjd=float(tmid),
                    rphase_int=rphase_int,
                    rphase_frac=rphase_frac,
                    f0=f0,
                    obs=obs,
                    span_min=seg_length_min,
                    coeffs=coeffs,
                    freq_mhz=obs_freq_mhz,
                    dm=dm,
                )
            )
        pc = cls(entries)
        # report worst fit error
        worst = pc._check(model_phase=(pn, frac, mjds), nn=nn)
        log.info(
            f"generated {nseg} polyco segments ({seg_length_min} min, "
            f"{ncoeff} coeffs); worst node error {worst:.2e} turns"
        )
        return pc

    def _check(self, model_phase, nn: int) -> float:
        pn, frac, mjds = model_phase
        worst = 0.0
        for s, e in enumerate(self.entries):
            sl = slice(s * nn, (s + 1) * nn)
            pred = e.phase(mjds[sl])
            truth = np.asarray(pn[sl], np.longdouble) + np.asarray(frac[sl], np.longdouble)
            worst = max(worst, float(np.max(np.abs(np.asarray(pred - truth, float)))))
        return worst

    # --- TEMPO polyco.dat IO --------------------------------------------------------

    def write(self, path: str) -> None:
        """TEMPO polyco.dat format (reference polycos.py tempo writer),
        provenance-stamped with ``#`` comment lines ``read`` skips."""
        from pint_tpu.utils.provenance import provenance_header

        with open(path, "w") as f:
            f.write(provenance_header("polyco"))
            for e in self.entries:
                f.write(
                    f"{e.psr:<12s} {'---':>9s} {'0.00':>10s} "
                    f"{e.tmid_mjd:.11f} {e.dm:.6f} 0.000 0.000\n"
                )
                rphase = f"{e.rphase_int + e.rphase_frac:.6f}"
                f.write(
                    f"{rphase:>20s} {e.f0:18.12f} {e.obs:>5s}"
                    f" {int(e.span_min):5d} {len(e.coeffs):5d}"
                    f" {e.freq_mhz:10.3f}\n"
                )
                for i in range(0, len(e.coeffs), 3):
                    f.write(
                        "".join(f"{c:25.17e}" for c in e.coeffs[i : i + 3]) + "\n"
                    )

    @classmethod
    def read(cls, path: str) -> "Polycos":
        """Parse a TEMPO polyco.dat (reference polycos.py tempo_polyco_table_reader)."""
        entries = []
        with open(path) as f:
            # '#' lines are provenance/comment headers, not segment data
            lines = [ln.rstrip("\n") for ln in f
                     if ln.strip() and not ln.lstrip().startswith("#")]
        i = 0
        while i < len(lines):
            h1 = lines[i].split()
            psr = h1[0]
            tmid = float(h1[3])
            dm = float(h1[4]) if len(h1) > 4 else 0.0
            h2 = lines[i + 1]
            parts = h2.split()
            rphase_s = parts[0]
            rphase_int = int(float(rphase_s) // 1)
            rphase_frac = float(rphase_s) - rphase_int
            f0 = float(parts[1])
            obs = parts[2]
            span = float(parts[3])
            ncoeff = int(parts[4])
            freq = float(parts[5]) if len(parts) > 5 else 0.0
            ncl = (ncoeff + 2) // 3
            coeffs = []
            for j in range(ncl):
                coeffs.extend(
                    float(x.replace("D", "e").replace("d", "e"))
                    for x in lines[i + 2 + j].split()
                )
            entries.append(
                PolycoEntry(
                    psr=psr, tmid_mjd=tmid, rphase_int=rphase_int,
                    rphase_frac=rphase_frac, f0=f0, obs=obs, span_min=span,
                    coeffs=np.asarray(coeffs[:ncoeff]), freq_mhz=freq, dm=dm,
                )
            )
            i += 2 + ncl
        return cls(entries)
