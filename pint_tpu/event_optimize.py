"""Photon-template MCMC fitting of timing models ("event_optimize").

Reference: pint/scripts/event_optimize.py (emcee_fitter:250, the
profile_likelihood:148 of Pletsch & Clark 2015 eq. 2,
marginalize_over_phase:167) and pint/mcmc_fitter.py:60-78 — the flagship
consumer of the photon-event stack: fit a timing model directly to photon
event phases against a pulse-profile template, with no TOAs formed.

TPU re-design: the whole posterior — timing-model phase chain over every
photon, wrapped-Gaussian template density, weighted Pletsch-Clark
likelihood, Gaussian/uniform priors — is ONE pure jax function of the
parameter vector theta = [delta timing params..., PHASE]. Walkers are a
vmapped batch axis and the entire chain is one `lax.scan` compiled program
(pint_tpu/sampler.py), where the reference drives emcee through a Python
callback per walker-step. Phase marginalization is a vmapped grid scan +
host parabolic refinement. Chains checkpoint to .npz and resume exactly.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.wls import apply_delta
from pint_tpu.models.base import leaf_to_f64
from pint_tpu.residuals import Residuals
from pint_tpu.sampler import run_ensemble
from pint_tpu.templates import (
    LCTemplate,
    lnlikelihood,
    template_density_jnp,
    template_params,
)
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.event_optimize")


def profile_lnlikelihood(phases, template: LCTemplate, weights=None):
    """Pletsch & Clark (2015) eq. 2 photon log-likelihood at fixed phases
    (host convenience; the jitted path lives in EventOptimizer)."""
    return lnlikelihood(template, phases, weights)


def marginalize_over_phase(phases, template: LCTemplate, weights=None,
                           resolution: float = 1.0 / 1024):
    """(best phase offset in cycles, max lnlike): the offset to ADD to the
    phases to align them with the template (reference
    event_optimize.py:167 returns bins; we return cycles directly).

    Delegates to templates.fit_phase_shift, whose dphi is the shift of the
    DATA relative to the template — hence the sign flip."""
    from pint_tpu.templates import fit_phase_shift

    n = max(int(round(1.0 / resolution)), 64)
    dphi, _err, l0 = fit_phase_shift(template, phases, weights, n_grid=n)
    return float((-dphi) % 1.0), float(l0)


class EventOptimizer:
    """MCMC fit of a timing model to photon events against a template.

    Parameters mirror the reference emcee_fitter (event_optimize.py:250):
    free timing parameters + a PHASE offset term, Gaussian priors of width
    parfile-uncertainty * priorerrfact (uniform special cases for
    SINI/ECC/PX, reference :686-696), initial walker ball scaled by
    parfile uncertainties * initerrfact.
    """

    def __init__(self, toas, model, template: LCTemplate, weights=None,
                 phserr: float = 0.03, priorerrfact: float = 10.0):
        self.toas = toas
        self.model = model
        self.template = template
        self.weights = None if weights is None else np.asarray(weights, float)
        self.free = tuple(model.free_params)
        self.fitkeys = list(self.free) + ["PHASE"]
        self.phserr = phserr
        self.resids = Residuals(toas, model, subtract_mean=False,
                                track_mode="nearest")
        # composite support (reference CompositeMCMCFitter,
        # mcmc_fitter.py:536): lnlike = sum_i setweight_i * lnlike_i; the
        # primary dataset is entry 0
        self.datasets: list[dict] = [{
            "toas": toas, "resids": self.resids, "template": template,
            "weights": self.weights, "setweight": 1.0,
        }]
        self.scales = np.array([
            model.param_meta[n].uncertainty or _default_scale(model, n)
            for n in self.free
        ] + [phserr])
        self._priorerrfact = priorerrfact
        self.chain: np.ndarray | None = None  # (nsteps, nwalkers, ndim)
        self.lnp: np.ndarray | None = None
        self.maxpost_theta: np.ndarray | None = None
        #: the chain's reference point: deltas are relative to the model
        #: state AT CONSTRUCTION (set_to_maxpost mutates the model, but the
        #: cached posterior keeps sampling around this fixed reference)
        self._params0 = model.xprec.convert_params(model.params)
        #: absolute offsets per theta component (chain walks deltas for the
        #: timing params, absolute cycles for PHASE)
        self.theta_offsets = np.array([
            float(np.asarray(leaf_to_f64(self._params0[n]))) for n in self.free
        ] + [0.0])

    # --- the jitted posterior --------------------------------------------------

    def add_dataset(self, toas, template: LCTemplate, weights=None,
                    setweight: float = 1.0) -> None:
        """Add another event dataset sharing the same timing model
        (reference CompositeMCMCFitter)."""
        self.datasets.append({
            "toas": toas,
            "resids": Residuals(toas, self.model, subtract_mean=False,
                                track_mode="nearest"),
            "template": template,
            "weights": None if weights is None else np.asarray(weights, float),
            "setweight": float(setweight),
        })
        self._lnpost_cached = None  # the posterior now spans more data

    def lnpost_fn(self):
        # memoized: run_ensemble caches its compiled chain on the callable
        # identity, so repeated fit()/resume calls must hand back the SAME
        # closure to skip re-tracing the whole photon posterior
        cached = getattr(self, "_lnpost_cached", None)
        if cached is not None:
            return cached
        model = self.model
        free = self.free
        params0 = self._params0
        dsets = [
            {
                "tensor": d["resids"].tensor,
                "tpl": tuple(jnp.asarray(a) for a in
                             template_params(d["template"])),
                "w": None if d["weights"] is None else jnp.asarray(d["weights"]),
                "sw": d["setweight"],
            }
            for d in self.datasets
        ]
        # prior table (reference event_optimize.py:686-696): uniform for
        # SINI/ECC/PX-style bounded params, Gaussian elsewhere
        v0 = np.array([float(np.asarray(leaf_to_f64(params0[n])))
                       for n in free])
        widths = self.scales[:-1] * self._priorerrfact
        kinds, lows, highs = [], [], []
        for n, v in zip(free, v0):
            base = n.rstrip("0123456789")
            if base in ("SINI", "E", "ECC"):
                kinds.append(1); lows.append(0.0); highs.append(1.0)
            elif base == "PX":
                kinds.append(1); lows.append(0.0); highs.append(10.0)
            elif base == "GLPH_":
                kinds.append(1); lows.append(-0.5); highs.append(1.0)
            else:
                kinds.append(0); lows.append(0.0); highs.append(0.0)
        kinds = np.array(kinds); lows = np.array(lows); highs = np.array(highs)
        wd = jnp.asarray(np.where(widths > 0, widths, 1.0))

        from pint_tpu.residuals import phase_residual_frac

        def frac_phases(pp, tensor):
            pn, r, _ = phase_residual_frac(
                model, pp, tensor, subtract_mean=False
            )
            return jnp.mod(r, 1.0)

        def lnpost(theta):
            d = theta[:-1]
            phs = theta[-1]
            x = jnp.asarray(v0) + d
            # priors
            lp = jnp.where(
                jnp.asarray(kinds) == 1,
                jnp.where(
                    (x >= jnp.asarray(lows)) & (x <= jnp.asarray(highs)),
                    0.0, -jnp.inf,
                ),
                -0.5 * (d / wd) ** 2,
            ).sum()
            lp = lp + jnp.where((phs >= 0.0) & (phs <= 1.0), 0.0, -jnp.inf)
            pp = apply_delta(params0, free, d)
            ll = 0.0
            for ds in dsets:
                ph = frac_phases(pp, ds["tensor"]) + phs
                f = template_density_jnp(ph, *ds["tpl"])
                w = ds["w"]
                if w is None:
                    li = jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
                else:
                    li = jnp.sum(jnp.log(jnp.maximum(w * f + 1.0 - w, 1e-300)))
                ll = ll + ds["sw"] * li
            return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)

        self._lnpost_cached = lnpost
        return lnpost

    # --- phases / diagnostics --------------------------------------------------

    def _ref_phases(self, index: int) -> np.ndarray:
        """Model phases mod 1 at the chain's reference params (delta=0)."""
        from pint_tpu.residuals import phase_residual_frac

        d = self.datasets[index]
        _, r, _ = phase_residual_frac(
            self.model, self._params0, d["resids"].tensor, subtract_mean=False
        )
        return np.mod(np.asarray(r), 1.0)

    def get_event_phases(self, index: int | None = None) -> np.ndarray:
        """Absolute model phases mod 1 at the CURRENT model params; all
        datasets concatenated, or one by index (reference
        CompositeMCMCFitter.get_event_phases)."""
        sel = self.datasets if index is None else [self.datasets[index]]
        phs = []
        for d in sel:
            r = Residuals(d["toas"], self.model, subtract_mean=False,
                          track_mode="nearest", tensor=d["resids"].tensor)
            phs.append(np.mod(np.asarray(r.phase_resids), 1.0))
        return np.concatenate(phs)

    def htest(self) -> float:
        from pint_tpu.eventstats import hm, hmw

        ph = self.get_event_phases()
        if all(d["weights"] is None for d in self.datasets):
            return hm(ph)
        w = np.concatenate([
            d["weights"] if d["weights"] is not None
            else np.ones(len(d["toas"]))
            for d in self.datasets
        ])
        return hmw(ph, w)

    # --- the chain -------------------------------------------------------------

    def fit(self, nwalkers: int = 100, nsteps: int = 500, burnin: int = 100,
            seed: int = 0, phs0: float | None = None,
            initerrfact: float = 0.1, backend: str | None = None,
            resume: bool = False):
        """Run (or resume) the ensemble chain; sets the model to the
        maximum-posterior sample and returns (samples, errors dict)."""
        ndim = len(self.fitkeys)
        nwalkers = max(nwalkers, 2 * ndim + 2)
        if nwalkers % 2:
            nwalkers += 1
        prev_chain = prev_lnp = None
        if resume and backend and os.path.exists(backend):
            with np.load(backend) as z:
                if list(z["fitkeys"]) != self.fitkeys:
                    raise ValueError(
                        f"backend {backend} fitkeys mismatch: {list(z['fitkeys'])}"
                    )
                prev_chain, prev_lnp = z["chain"], z["lnp"]
                seed = int(z["next_seed"])
            x0 = prev_chain[-1]
            if x0.shape[0] != nwalkers:
                raise ValueError(
                    f"backend has {x0.shape[0]} walkers, requested {nwalkers}"
                )
            log.info(f"resuming from {backend}: {prev_chain.shape[0]} steps done")
        else:  # fresh start: phase scan + walker ball (skipped on resume)
            if phs0 is None:
                phs0, ll0 = marginalize_over_phase(
                    self.get_event_phases(index=0), self.template, self.weights
                )
                log.info(f"starting pulse phase {phs0:.4f} (lnlike {ll0:.1f})")
            rng = np.random.default_rng(seed)
            x0 = rng.standard_normal((nwalkers, ndim)) * self.scales * initerrfact
            x0[:, -1] = (phs0 + rng.standard_normal(nwalkers) * self.phserr) % 1.0
            x0[0, :-1] = 0.0
            x0[0, -1] = phs0

        chain, lnp, acc = run_ensemble(self.lnpost_fn(), x0, nsteps, seed=seed)
        if prev_chain is not None:
            chain = np.concatenate([prev_chain, chain])
            lnp = np.concatenate([prev_lnp, lnp])
        self.chain, self.lnp = chain, lnp
        log.info(
            f"chain: {nwalkers} walkers x {chain.shape[0]} total steps, "
            f"acceptance {acc:.2f}"
        )
        if backend:
            np.savez_compressed(
                backend, chain=chain, lnp=lnp,
                fitkeys=np.array(self.fitkeys), next_seed=seed + 1,
            )

        i_best = np.unravel_index(np.argmax(lnp), lnp.shape)
        self.maxpost_theta = chain[i_best]
        flat = chain[burnin:].reshape(-1, ndim)
        # 68th-percentile |centered| errors (reference event_optimize.py:905)
        centered = flat - self.maxpost_theta
        errors = {
            k: float(np.percentile(np.abs(centered[:, i]), 68))
            for i, k in enumerate(self.fitkeys)
        }
        self.set_to_maxpost()
        return flat, errors

    def set_to_maxpost(self) -> None:
        """Write the max-posterior sample (timing part) into the model."""
        if self.maxpost_theta is None:
            raise RuntimeError("run fit() first")
        from pint_tpu.ops.xprec import params_to_dd

        pp = apply_delta(self._params0, self.free,
                         jnp.asarray(self.maxpost_theta[:-1]))
        self.model.params = params_to_dd(pp)


def _default_scale(model, name: str) -> float:
    """Fallback walker scale for params without parfile uncertainties."""
    v = abs(float(np.asarray(leaf_to_f64(model.params[name]))))
    return max(v * 1e-8, 1e-12)
