"""Chi^2 grid scans as one compiled SPMD program.

Reference: pint/gridutils.py:156 (grid_chisq) — the reference deep-copies the
fitter per grid point and refits in a process pool; its own profiling shows
~82% of wall time in design-matrix construction + residual evaluation
(profiling/README.txt:62-71, 176.4 s for a 3x3 grid).

TPU re-design: ONE jitted program evaluates every grid point.

- Per grid point: fix the gridded parameters, run `maxiter` Gauss-Newton
  refits of the remaining free parameters (design matrix via jacfwd through
  the extended-precision phase chain, normal equations on the MXU,
  Cholesky solve), return chi^2.
- Grid points are a `vmap` batch axis (single chip) and/or a sharded mesh
  axis (multi chip).
- The TOA axis can additionally be sharded over the mesh: weighted means,
  column norms, normal equations G = A^T A, c = A^T b and the final chi^2
  all reduce with `jax.lax.psum` over the `toa` mesh axis, so the collectives
  ride ICI while each chip only ever touches its TOA block.

TZR anchoring under TOA sharding: the fiducial TZR row (which the model
subtracts from every phase, models/timing_model.py:228-232) is REPLICATED
into every TOA shard as its last local row, so each shard anchors locally
and no broadcast of the TZR phase is needed.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from pint_tpu.fitting.wls import apply_delta
from pint_tpu.fitting.woodbury import cinv_apply, s_factor, woodbury_chi2
from pint_tpu.residuals import phase_residual_frac
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.gridutils")

Array = jnp.ndarray

# Levenberg-style damping on the equilibrated normal equations. The grid
# kernel takes ONE (or few) Gauss-Newton steps from parameters that sit far
# off-minimum at the outer grid points, where an undamped step along
# near-degenerate directions (equilibrated-G eigenvalues ~1e-10 of the
# diagonal on small problems) is pure noise — a fixed lambda = 1e-6 damps
# exactly those directions (fully suppressed below eigenvalue ~1e-6, i.e.
# singular values below ~1e-3 of the strongest; <0.1% bias above 1e-3).
# NOTE this is deliberately stronger than the 1e-12 ridge of the converging
# fitters (fitting/gls.py), which iterate to the minimum where damping bias
# matters; the reference grid refit is likewise a fresh WLS solve with an
# SVD threshold (fitter.py:2186-2246). The damping also bounds
# cond(G + lambda) <= 1e6, which is what makes the SHARDED grid
# reproducible: the solve amplifies psum-vs-local reduction-order noise by
# cond(G), so the round-3 unregularized kernel turned 1e-16 reduction noise
# into 6e-7 chi^2 differences, while this kernel holds sharded-vs-single
# parity at ~1e-11 (asserted by __graft_entry__.dryrun_multichip).
_RIDGE = 1e-6


def _point_kernel(model, grid_names, free, subtract_mean, maxiter, toa_axis=None,
                  correlated=False):
    """Pure per-grid-point chi^2 kernel.

    kernel(vals, params, data) -> scalar chi^2, where
      vals : (len(grid_names),) f64 values (model-internal units)
      params : xprec-converted parameter pytree (replicated)
      data : dict with 'tensor' (model tensor, rows possibly a TOA shard),
             'w' (1/err^2, zero on padding rows), 'track_pn',
             'delta_pn' (either may be None).

    With `toa_axis` set, every reduction over the TOA axis is completed with
    a psum over that mesh axis, making the kernel valid inside shard_map.
    """
    from pint_tpu.fitting.design import linear_columns, linear_split

    xp = model.xprec
    mean_free = subtract_mean and not model.has_phase_offset
    p = len(free)
    nonlin, lin_names, owners = linear_split(model, free)
    sl_data = slice(None, -1) if model.has_abs_phase else slice(None)

    def _reduce(x):
        s = jnp.sum(x, axis=0)
        if toa_axis is not None:
            s = jax.lax.psum(s, toa_axis)
        return s

    def _reduce_mat(m):
        if toa_axis is not None:
            m = jax.lax.psum(m, toa_axis)
        return m

    def time_resids_f(params, data):
        _, r, f = phase_residual_frac(
            model,
            params,
            data["tensor"],
            track_pn=data["track_pn"],
            delta_pn=data["delta_pn"],
            subtract_mean=False,
        )
        r = r / f
        if mean_free:
            w = data["w"]
            r = r - _reduce(w * r) / _reduce(w)
        return r, f

    def time_resids(params, data):
        return time_resids_f(params, data)[0]

    def gn_step(params, data):
        """One GLS/WLS Gauss-Newton refit: hybrid design matrix (autodiff
        over the nonlinear params + analytic columns for the linear
        families, fitting/design.py); with correlated noise the marginalized
        normal equations apply C^-1 through the structured Woodbury algebra
        (same as fitting/gls.py)."""

        def rfun(delta):
            return time_resids_f(apply_delta(params, nonlin, delta), data)

        z = jnp.zeros(len(nonlin))
        (r0, f0), jvp = jax.linearize(rfun, z)
        cols = {}
        if nonlin:
            M_nl = jax.vmap(jvp)(jnp.eye(len(nonlin)))[0].T
            for i, n in enumerate(nonlin):
                cols[n] = M_nl[:, i]
        if lin_names:
            M_l = linear_columns(model, params, data["tensor"], f0, sl_data,
                                 lin_names, owners)
            if mean_free:
                w = data["w"]
                M_l = M_l - _reduce(w[:, None] * M_l) / _reduce(w)
            for i, n in enumerate(lin_names):
                cols[n] = M_l[:, i]
        M = jnp.stack([cols[n] for n in free], axis=1)  # (N_local, p)
        w = data["w"]
        # global column equilibration (reference fitter.py:2186)
        col2 = _reduce(w[:, None] * M * M)
        norm = jnp.sqrt(jnp.where(col2 == 0, 1.0, col2))
        Mn = M / norm
        # marginalized normal equations, C^-1 via structured Woodbury
        # (fitting/woodbury.py); segment-sums/contractions are local to the
        # TOA shard and completed with psum
        if correlated:
            basis = model.noise_basis_and_weights(params, data["tensor"])
            sf = s_factor(basis, w, reduce=_reduce_mat) if basis is not None else None
            CinvM = cinv_apply(basis, w, Mn, sf, reduce=_reduce_mat)
        else:
            CinvM = w[:, None] * Mn
        G = _reduce_mat(Mn.T @ CinvM) + _RIDGE * jnp.eye(p)
        c = _reduce_mat(CinvM.T @ (-r0))
        dx = jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(G), c) / norm
        return apply_delta(params, free, dx, project_domain=True)

    def kernel(vals, params, data):
        params = dict(params)
        for i, n in enumerate(grid_names):
            params[n] = xp.lift(vals[i])
        for _ in range(maxiter if free else 0):
            params = gn_step(params, data)
        r = time_resids(params, data)
        w = data["w"]
        if not correlated:
            return _reduce(w * r * r)
        # Woodbury GLS chi^2 (fitting/gls.py docstring), structured basis
        basis = model.noise_basis_and_weights(params, data["tensor"])
        chi2, _ = woodbury_chi2(basis, w, r, reduce=_reduce_mat)
        return chi2

    return kernel


def _host_data(resids, tensor):
    """Assemble the kernel's data dict from a Residuals object (host side)."""
    w = 1.0 / np.asarray(resids.errors_s) ** 2
    return {
        "tensor": tensor,
        "w": jnp.asarray(w),
        "track_pn": resids._track_pn,
        "delta_pn": resids._delta_pn,
    }


def _shard_data_host(model, data, n_shards):
    """Re-lay the TOA axis of `data` into `n_shards` equal blocks.

    Each block is [chunk data rows ..., (pad rows), TZR row?]; pad rows get
    w = 0 so they drop out of every reduction. Returns
    (data', specs') where specs' marks each leaf sharded (True) or
    replicated (False). The row layout itself is shared with the fused
    sharded fitters (fitting/sharded.py shard_fit_rows).
    """
    from pint_tpu.fitting.sharded import shard_fit_rows

    vecs = {"w": data["w"], "track_pn": data["track_pn"],
            "delta_pn": data["delta_pn"]}
    tensor_out, vecs_out, row_keys = shard_fit_rows(
        model, data["tensor"], vecs, n_shards)
    out = {"tensor": tensor_out, **vecs_out}
    sharded = {
        "tensor": {k: k in row_keys for k in tensor_out},
        "w": True,
        "track_pn": None if data["track_pn"] is None else True,
        "delta_pn": None if data["delta_pn"] is None else True,
    }
    return out, sharded


def grid_chisq(
    fitter,
    parnames,
    parvalues,
    maxiter: int = 1,
    mesh=None,
    grid_axis: str = "grid",
    toa_axis: str = "toa",
    batch: int | None = None,
):
    """Chi^2 over a parameter grid, refitting all other free parameters.

    Mirrors the reference API (pint/gridutils.py:156): `parnames` is a tuple
    of fittable parameter names, `parvalues` a matching tuple of 1-D value
    arrays (model-internal units); the result has shape
    ``np.meshgrid(*parvalues)`` — i.e. ``(len(parvalues[1]),
    len(parvalues[0]), ...)`` for the default 'xy' indexing.

    maxiter : Gauss-Newton refit iterations per grid point (the reference
        WLSFitter.fit_toas default is one full linear step).
    mesh : optional `jax.sharding.Mesh`. Axis `grid_axis` shards the
        flattened grid points; axis `toa_axis` (if present in the mesh)
        additionally shards the TOA rows, with psum collectives completing
        every reduction.
    batch : grid points evaluated concurrently per chip (vmap width); the
        rest of the grid streams through `lax.map`. Default: everything at
        once below 64 points, else 16 per chip.
    """
    if len(parnames) != len(parvalues):
        raise ValueError(
            f"{len(parnames)} parameter names but {len(parvalues)} value arrays"
        )
    grids = np.meshgrid(*[np.asarray(v, np.float64) for v in parvalues])
    out_shape = grids[0].shape
    pts = np.stack([g.ravel() for g in grids], axis=1)  # (npts, g)
    chi2 = grid_chisq_points(
        fitter, parnames, pts, maxiter=maxiter, mesh=mesh,
        grid_axis=grid_axis, toa_axis=toa_axis, batch=batch,
    )
    return chi2.reshape(out_shape)


def grid_chisq_points(
    fitter,
    parnames,
    points,
    maxiter: int = 1,
    mesh=None,
    grid_axis: str = "grid",
    toa_axis: str = "toa",
    batch: int | None = None,
):
    """Chi^2 at an ARBITRARY set of parameter points: `points` is
    (npts, len(parnames)) in model-internal units. The shared engine under
    grid_chisq / grid_chisq_derived."""
    model = fitter.model
    resids = fitter.resids
    for n in parnames:
        if n not in model.param_meta:
            raise KeyError(f"unknown parameter {n}")
    free = tuple(n for n in model.free_params if n not in parnames)

    pts = np.asarray(points, np.float64)
    if pts.ndim != 2 or pts.shape[1] != len(parnames):
        raise ValueError(
            f"points must be (npts, {len(parnames)}) for parameters "
            f"{tuple(parnames)}; got shape {pts.shape}"
        )
    npts = pts.shape[0]

    # the chi^2 STATISTIC follows the fitter type, like the reference's
    # per-fitter grids: GLS fitters grid the Woodbury/correlated statistic,
    # WLS fitters the plain weighted chi^2 even when the model carries
    # noise components (reference bench_chisq_grid vs _WLSFitter)
    from pint_tpu.fitting.gls import GLSFitter

    correlated = isinstance(fitter, GLSFitter) and model.has_correlated_errors

    params = model.xprec.convert_params(model.params)
    data = _host_data(resids, fitter.tensor)

    if mesh is not None:
        chi2 = _grid_sharded(
            model, parnames, free, resids.subtract_mean, maxiter, mesh,
            grid_axis, toa_axis, pts, params, data, correlated,
        )
    else:
        chi2 = _grid_single(
            model, parnames, free, resids.subtract_mean, maxiter, pts,
            params, data, batch, correlated,
        )
    return np.asarray(chi2)[:npts]


def grid_chisq_derived(
    fitter,
    parnames,
    parfuncs,
    gridvalues,
    maxiter: int = 1,
    mesh=None,
    grid_axis: str = "grid",
    toa_axis: str = "toa",
    batch: int | None = None,
):
    """Chi^2 over a grid of DERIVED parameters (reference
    gridutils.py:382): `parfuncs[i]` maps the meshgridded `gridvalues` to
    the model parameter `parnames[i]` (e.g. grid over (Mp, Mc) while the
    model is fit in (M2, SINI)).

    Returns (chi2 array shaped like the meshgrid, [parvalues arrays]).
    """
    if len(parnames) != len(parfuncs):
        raise ValueError("parnames and parfuncs must pair up")
    grids = np.meshgrid(*[np.asarray(v, np.float64) for v in gridvalues])
    out_shape = grids[0].shape
    parvalues = [np.asarray(f(*grids), np.float64) for f in parfuncs]
    pts = np.stack([v.ravel() for v in parvalues], axis=1)
    chi2 = grid_chisq_points(
        fitter, parnames, pts, maxiter=maxiter, mesh=mesh,
        grid_axis=grid_axis, toa_axis=toa_axis, batch=batch,
    )
    return chi2.reshape(out_shape), parvalues


def _grid_tiles(pts, batch):
    npts = pts.shape[0]
    if batch is None:
        batch = npts if npts <= 64 else 16
    batch = min(batch, npts)
    n_pad = (-npts) % batch
    if n_pad:
        pts = np.concatenate([pts, np.repeat(pts[-1:], n_pad, axis=0)])
    return jnp.asarray(pts.reshape(-1, batch, pts.shape[1])), batch


def _grid_single_fn(model, parnames, free, subtract_mean, maxiter, batch,
                    correlated):
    """The compiled-program cache entry for a single-chip grid scan:
    repeated scans (bench repeats, profile sweeps) must not
    re-trace/re-compile. A TimedProgram, so the grid program runs through
    the jaxpr auditor like every fit program (single-chip scan: no
    collective may appear), precompile_grid's AOT executable lands in the
    per-signature cache, and the compile cost shows up split out in any
    collecting perf report."""
    from pint_tpu.ops.compile import TimedProgram, precision_jit

    cache = model.__dict__.setdefault("_grid_fn_cache", {})
    key = ("single", parnames, free, subtract_mean, maxiter, batch,
           correlated, model.xprec.name)
    if key not in cache:
        kernel = _point_kernel(model, parnames, free, subtract_mean, maxiter,
                               correlated=correlated)
        vk = jax.vmap(kernel, in_axes=(0, None, None))
        cache[key] = TimedProgram(
            precision_jit(
                lambda tiles, params, data: jax.lax.map(
                    lambda t: vk(t, params, data), tiles)
            ),
            "grid",
            precision_spec=model.xprec.name,
            # closure = model structure + the scan config already in the
            # cache key: AOT-serializable (ops/compile.py artifact store)
            aot_key=f"{model.aot_structure_key()}|{key!r}",
        )
    return cache[key], key


def _grid_single(model, parnames, free, subtract_mean, maxiter, pts, params, data,
                 batch, correlated):
    tiles, batch = _grid_tiles(pts, batch)
    fn, key = _grid_single_fn(model, parnames, free, subtract_mean, maxiter,
                              batch, correlated)
    # an executable precompiled for this exact tile shape (precompile_grid)
    # is served from the TimedProgram's per-signature cache; other shapes
    # reach the shape-polymorphic jit wrapper
    return fn(tiles, params, data).reshape(-1)


def precompile_grid(fitter, parnames, parvalues, maxiter: int = 1,
                    batch: int | None = None):
    """Ahead-of-time compile the grid program for the given scan shape.

    Compilation is host-side work: calling this from a worker thread while
    the chip is busy (e.g. running the initial fit) overlaps the two, so
    the first `grid_chisq` call finds the executable ready. The compiled
    program lands in the same in-process cache `grid_chisq` uses; the
    persistent XLA cache makes repeat processes cheap too.

    Thread-safe with respect to a concurrent fit: it touches only the
    model's structure (read-only) and jax's compiler. Returns the number
    of grid points the compiled program covers.
    """
    from pint_tpu.fitting.gls import GLSFitter

    model = fitter.model
    grids = np.meshgrid(*[np.asarray(v, np.float64) for v in parvalues])
    pts = np.stack([g.ravel() for g in grids], axis=1)
    free = tuple(n for n in model.free_params if n not in parnames)
    correlated = isinstance(fitter, GLSFitter) and model.has_correlated_errors
    tiles, batch = _grid_tiles(pts, batch)
    fn, key = _grid_single_fn(model, tuple(parnames), free,
                              fitter.resids.subtract_mean, maxiter, batch,
                              correlated)
    params = model.xprec.convert_params(model.params)
    data = _host_data(fitter.resids, fitter.tensor)
    # TimedProgram.precompile lowers (through the jaxpr auditor), compiles
    # under the perf "compile" stage, and caches the executable for this
    # exact tile-shape signature — the next grid_chisq call finds it ready
    fn.precompile(tiles, params, data)
    return pts.shape[0]


def _shard_map():
    """jax.shard_map across jax versions (shared helper,
    fitting/sharded.py)."""
    from pint_tpu.fitting.sharded import _shard_map as fn

    return fn()


def _grid_sharded(model, parnames, free, subtract_mean, maxiter, mesh,
                  grid_axis, toa_axis, pts, params, data, correlated):
    from jax.sharding import PartitionSpec as P

    shard_map = _shard_map()

    if grid_axis not in mesh.shape:
        raise ValueError(f"mesh has no axis {grid_axis!r}")
    n_grid = mesh.shape[grid_axis]
    shard_toas = toa_axis in mesh.shape and mesh.shape[toa_axis] > 1
    eff_toa_axis = toa_axis if shard_toas else None

    npts = pts.shape[0]
    n_pad = (-npts) % n_grid
    if n_pad:
        pts = np.concatenate([pts, np.repeat(pts[-1:], n_pad, axis=0)])
    pts = jnp.asarray(pts)

    if shard_toas:
        data, sharded = _shard_data_host(model, data, mesh.shape[toa_axis])
        data_specs = jax.tree.map(
            lambda s: P(toa_axis) if s else P(), sharded,
            is_leaf=lambda x: isinstance(x, bool),
        )
    else:
        data_specs = jax.tree.map(lambda _: P(), data)

    from pint_tpu.ops.compile import TimedProgram, precision_jit

    cache = model.__dict__.setdefault("_grid_fn_cache", {})
    key = ("sharded", parnames, free, subtract_mean, maxiter,
           grid_axis, toa_axis, tuple(mesh.devices.flat),
           tuple(sorted(mesh.shape.items())), shard_toas, correlated,
           model.xprec.name)
    if key not in cache:
        kernel = _point_kernel(model, parnames, free, subtract_mean, maxiter,
                               toa_axis=eff_toa_axis, correlated=correlated)
        vk = jax.vmap(kernel, in_axes=(0, None, None))
        param_specs = jax.tree.map(lambda _: P(), params)
        fn = shard_map(
            vk,
            mesh=mesh,
            in_specs=(P(grid_axis), param_specs, data_specs),
            out_specs=P(grid_axis),
            check_vma=False,
        )
        # auditor contract: with the TOA axis sharded the reductions MUST
        # psum over it; a grid-axis-only mesh is embarrassingly parallel
        # and must contain no collective
        cache[key] = TimedProgram(
            precision_jit(fn), "grid_sharded",
            collective_axes=(toa_axis,) if shard_toas else (),
            precision_spec=model.xprec.name,
            # closure = model structure + mesh/scan config (the cache
            # key, device ids included): AOT-serializable
            aot_key=f"{model.aot_structure_key()}|{key!r}",
        )
    return cache[key](pts, params, data)
