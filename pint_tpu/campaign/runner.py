"""Durable, resumable campaigns: preemption-safe long-running device work.

A *campaign* is hours of device work decomposed into **content-keyed
work units** — one unit per chain / grid point / injection realization —
executed by a :class:`CampaignRunner` that makes every completed unit
durable the moment it finishes. The failure mode this kills is the
canonical one on shared TPU fleets: a preempted process losing a whole
B×C sampling run because nothing between "started" and "finished" ever
reached disk.

The durability discipline is the serving stack's (serve/journal.py,
serve/recover.py), reused verbatim:

- **Unit results** — ``<dir>/results/<uid>.ckpt`` — crc-framed pickles
  written via the shared atomic writer (``_write_checkpoint``: tmp +
  fsync + rename; a kill mid-write leaves a torn ``.tmp`` and an intact
  previous generation). A result that fails its crc on resume is
  quarantined beside the store with ``campaign.checkpoint_corrupt`` on
  the degradation ledger and the unit re-runs — garbage is never
  restored.
- **Progress snapshots** — ``<dir>/snapshots/snapshot-NNNNNN.ckpt`` —
  generational (``PINT_TPU_CAMPAIGN_KEEP`` kept, >= 2) campaign state
  written every ``PINT_TPU_CAMPAIGN_CHECKPOINT_EVERY`` completed units:
  done/total, cumulative wall, status. ``pint_tpu status --campaign``
  and the metrics gauges read these.
- **The campaign ledger** — ``<dir>/ledger/`` — a
  :class:`~pint_tpu.serve.journal.RequestJournal` of marker records
  (``resumed``, ``unit_done``, ``snapshot``, ``campaign_status``), so
  "what happened to this campaign" is answerable from disk with the
  same framing + quarantine discipline as the serving WAL.

**Bitwise resume.** Work units are WHOLE deterministic computations:
chain c's entire trajectory depends only on ``fold_in(seed, chain_id)``
(fitting/noise_like.py locks fleet ≡ solo per chain id), a grid point
only on its coordinates. Resume therefore skips completed units and
re-runs incomplete ones from their seeds — the assembled result is
**bitwise-equal** to an uninterrupted run, proven by the
kill-mid-campaign drill (tests/test_campaign.py): SIGKILL between
checkpoints, resume in a fresh process, sha256 over the raw result
bytes identical to the never-killed twin's.

**Graceful drain.** SIGTERM/SIGINT (the preemption notice) set a drain
flag: the runner finishes the unit in flight, snapshots, writes the
ledger marker and returns status ``preempted`` — the next process
resumes. A SIGKILL (no notice) loses only the unit in flight.

Every resume is ledger-visible (``campaign.resumed``, refusable under
``PINT_TPU_DEGRADED=error``), on the flight recorder, and counted in
the metrics registry; live gauges export units done/total, checkpoint
age and ETA so ``pint_tpu status --campaign <dir>`` answers "how far
along and when did it last checkpoint". Wall attribution lands in
:func:`pint_tpu.ops.perf.campaign_breakdown` (>= 90% named).
"""

from __future__ import annotations

import hashlib
import importlib
import json
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from pint_tpu.obs import flight, metrics as obs_metrics
from pint_tpu.ops import degrade, perf
from pint_tpu.serve.journal import (JournalError, RequestJournal,
                                    replay_records)
from pint_tpu.serve.recover import _read_checkpoint, _write_checkpoint
from pint_tpu.testing import faults
from pint_tpu.utils import knobs
from pint_tpu.utils.logging import get_logger

log = get_logger("pint_tpu.campaign")

__all__ = ["CampaignRunner", "WorkUnit", "campaign_status",
           "content_key", "register_kind", "resolve_kind", "work_unit"]


def content_key(kind: str, payload: dict) -> str:
    """The unit's identity: sha256 over the canonical JSON of (kind,
    payload). Two units with the same key compute the same thing — the
    resume scan keys durable results on it, so a manifest edit that
    changes a unit's inputs changes its key and forces a re-run."""
    blob = json.dumps({"kind": kind, "payload": payload},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclass(frozen=True)
class WorkUnit:
    """One content-keyed unit of campaign work: ``kind`` names a
    registered executor (or a ``module:function`` entry point — the
    manifest must be resolvable in a FRESH process), ``payload`` is its
    JSON-able argument dict, ``uid`` the content key."""

    kind: str
    payload: dict = field(default_factory=dict)
    uid: str = ""


def work_unit(kind: str, **payload) -> WorkUnit:
    """Build a :class:`WorkUnit` with its content key computed."""
    return WorkUnit(kind, payload, content_key(kind, payload))


# -- the unit-kind registry ---------------------------------------------------------

_KINDS: dict = {}


def register_kind(name: str):
    """Decorator registering a unit executor under ``name``. Executors
    take the payload dict and return a picklable result; they must be
    DETERMINISTIC in the payload (seeds ride the payload) — that is
    what makes resume bitwise-equal to an uninterrupted run."""
    def deco(fn):
        _KINDS[name] = fn
        return fn
    return deco


def resolve_kind(kind: str):
    """The executor for ``kind``: a registered name (the built-ins in
    campaign/sampling.py) or an importable ``module:function`` entry
    point — the form a manifest written by one process and resumed by
    another relies on."""
    from pint_tpu.campaign import sampling  # noqa: F401 — registers built-ins

    fn = _KINDS.get(kind)
    if fn is None and ":" in kind:
        mod, _, attr = kind.partition(":")
        fn = getattr(importlib.import_module(mod), attr, None)
    if fn is None:
        raise KeyError(
            f"unknown campaign unit kind {kind!r}; register it with "
            "pint_tpu.campaign.register_kind or name an importable "
            "module:function entry point")
    return fn


# -- helpers ------------------------------------------------------------------------

def _atomic_write_text(path: Path, text: str) -> None:
    tmp = path.with_suffix(".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


def _snap_index(path: Path) -> int:
    return int(path.stem.split("-")[-1])


class CampaignRunner:
    """Execute a campaign's work units with durable, resumable progress
    (see module docstring).

    First construction against a directory writes the manifest (the
    unit list with content keys, atomically); a later construction
    against the same directory — with or without ``units`` — loads it
    and becomes a RESUME: completed units are skipped after their
    durable results validate. Passing ``units`` whose content keys
    differ from the manifest's refuses loudly: a campaign directory
    holds exactly one campaign.
    """

    def __init__(self, dirpath: str | Path, units=None, *,
                 name: str = "campaign", checkpoint_every: int | None = None,
                 keep: int | None = None):
        self.dir = Path(dirpath)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.checkpoint_every = max(
            int(knobs.get("PINT_TPU_CAMPAIGN_CHECKPOINT_EVERY"))
            if checkpoint_every is None else int(checkpoint_every), 1)
        # keep >= 2: a kill mid-snapshot-write must always leave an
        # intact previous generation behind the atomic rename
        self.keep = max(int(knobs.get("PINT_TPU_CAMPAIGN_KEEP"))
                        if keep is None else int(keep), 2)
        manifest = self.dir / "manifest.json"
        if manifest.exists():
            man = json.loads(manifest.read_text())
            if units is not None:
                mine = [{"uid": u.uid, "kind": u.kind,
                         "payload": u.payload} for u in units]
                if mine != man["units"]:
                    raise ValueError(
                        f"campaign dir {self.dir} holds a DIFFERENT "
                        "campaign (content keys differ); use a fresh "
                        "directory per campaign")
            self.name = man["name"]
            self.units = [WorkUnit(d["kind"], d["payload"], d["uid"])
                          for d in man["units"]]
            self._fresh = False
        else:
            if units is None:
                raise ValueError(
                    f"{self.dir} has no campaign manifest and no units "
                    "were given")
            self.name = name
            self.units = list(units)
            _atomic_write_text(manifest, json.dumps({
                "name": name,
                "units": [{"uid": u.uid, "kind": u.kind,
                           "payload": u.payload} for u in self.units],
            }, indent=1))
            self._fresh = True
        self._done: set[str] = set()
        self._drain = False
        self._old_handlers: dict = {}
        self._gen = max((_snap_index(p) for p in
                         self._snap_dir.glob("snapshot-*.ckpt")),
                        default=0)
        self._last_snapshot_mono: float | None = None
        self._prior_wall_s = 0.0
        self._unit_s: list[float] = []
        self.ledger: RequestJournal | None = None
        self._register_gauges()

    # -- layout ------------------------------------------------------------------

    @property
    def _results_dir(self) -> Path:
        return self.dir / "results"

    @property
    def _snap_dir(self) -> Path:
        return self.dir / "snapshots"

    # -- durable state -----------------------------------------------------------

    def _scan_results(self) -> set[str]:
        """Validate every durable unit result: crc-clean ones are DONE;
        a corrupt one is quarantined beside the store
        (``campaign.checkpoint_corrupt``) and its unit re-runs. Torn
        ``.tmp`` files (kill-mid-write debris) are dropped — the rename
        never happened, the unit was never done."""
        rdir = self._results_dir
        rdir.mkdir(parents=True, exist_ok=True)
        known = {u.uid for u in self.units}
        done: set[str] = set()
        for p in sorted(rdir.glob("*.ckpt")):
            if p.stem not in known:
                continue               # a stray file is not campaign work
            try:
                _read_checkpoint(p)
            except Exception as e:  # noqa: BLE001 — quarantined + ledgered below, never silent  # jaxlint: disable=silent-except
                qdir = rdir / "quarantine"
                qdir.mkdir(parents=True, exist_ok=True)
                os.replace(p, qdir / p.name)
                degrade.record(
                    "campaign.checkpoint_corrupt", p.name,
                    f"unit result failed validation ({e}); preserved at "
                    f"{qdir / p.name}, the unit re-runs from its seed",
                    fix="none needed — the re-run rebuilds the exact "
                        "result from the unit's content-keyed payload")
                continue
            done.add(p.stem)
        for t in rdir.glob("*.tmp"):
            t.unlink(missing_ok=True)  # kill-mid-write debris
        return done

    def _latest_snapshot(self):
        """(snapshot dict, path) from the newest generation that loads
        clean; corrupt generations are quarantined
        (``campaign.checkpoint_corrupt``) and the previous one serves —
        the generational discipline the kill/corrupt drills prove."""
        sdir = self._snap_dir
        for p in sorted(sdir.glob("snapshot-*.ckpt"),
                        key=_snap_index, reverse=True):
            try:
                return _read_checkpoint(p), p
            except Exception as e:  # noqa: BLE001 — quarantined + ledgered below, never silent  # jaxlint: disable=silent-except
                qdir = sdir / "quarantine"
                qdir.mkdir(parents=True, exist_ok=True)
                os.replace(p, qdir / p.name)
                degrade.record(
                    "campaign.checkpoint_corrupt", p.name,
                    f"campaign snapshot failed validation ({e}); "
                    f"preserved at {qdir / p.name}, the previous "
                    "generation serves",
                    fix="none needed — snapshots are progress metadata; "
                        "unit results are the durable work product")
        return None, None

    def _snapshot(self, status: str = "running") -> Path:
        self._gen += 1
        self._snap_dir.mkdir(parents=True, exist_ok=True)
        path = self._snap_dir / f"snapshot-{self._gen:06d}.ckpt"
        wall = self._prior_wall_s + sum(self._unit_s)
        _write_checkpoint(path, {
            "name": self.name,
            "status": status,
            "done": sorted(self._done),
            "total": len(self.units),
            "wall_s": round(wall, 4),
            "t_unix": time.time(),
        })
        perf.add("campaign_checkpoints")
        self._last_snapshot_mono = time.monotonic()
        # prune to the newest `keep` generations — never fewer than 2,
        # so the latest write always has an intact predecessor
        snaps = sorted(self._snap_dir.glob("snapshot-*.ckpt"),
                       key=_snap_index)
        for p in snaps[:-self.keep]:
            p.unlink(missing_ok=True)
        return path

    # -- observability -----------------------------------------------------------

    def _register_gauges(self) -> None:
        reg = obs_metrics.registry()
        reg.gauge("campaign_units_total",
                  "work units in the campaign manifest",
                  fn=lambda: float(len(self.units)))
        reg.gauge("campaign_units_done",
                  "campaign units with a validated durable result",
                  fn=lambda: float(len(self._done)))
        reg.gauge("campaign_checkpoint_age_s",
                  "seconds since the last campaign progress snapshot "
                  "(-1 before the first)",
                  fn=self._checkpoint_age_s)
        reg.gauge("campaign_eta_s",
                  "estimated seconds to campaign completion at the "
                  "observed unit rate (-1 before the first unit)",
                  fn=self._eta_s)

    def _checkpoint_age_s(self) -> float:
        if self._last_snapshot_mono is None:
            return -1.0
        return round(time.monotonic() - self._last_snapshot_mono, 3)

    def _eta_s(self) -> float:
        if not self._unit_s:
            return -1.0
        per = sum(self._unit_s) / len(self._unit_s)
        return round(per * (len(self.units) - len(self._done)), 3)

    # -- preemption notice -------------------------------------------------------

    def _install_signals(self) -> None:
        """SIGTERM/SIGINT = the preemption notice: finish the unit in
        flight, snapshot, exit ``preempted``. Installed only on the
        main thread (signal.signal raises elsewhere); a SIGKILL drill
        simply never reaches this path."""
        if threading.current_thread() is not threading.main_thread():
            return

        def _drain_handler(signum, frame):
            self._drain = True
            flight.note("campaign.drain", name=self.name, signal=signum)
            log.warning(f"campaign {self.name!r}: drain requested "
                        f"(signal {signum}); finishing the unit in "
                        "flight, then snapshotting")

        for sig in (signal.SIGTERM, signal.SIGINT):
            self._old_handlers[sig] = signal.signal(sig, _drain_handler)

    def _restore_signals(self) -> None:
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)
        self._old_handlers.clear()

    # -- the run loop ------------------------------------------------------------

    def _mark(self, op: str, **fields) -> None:
        """A ledger write that never kills the campaign: the ledger is
        the EXPLANATION, the unit results are the work product. A shed
        write (journal disk full — serve.journal_full is already on the
        degradation ledger by the time JournalError surfaces) drops the
        marker and the campaign keeps computing."""
        try:
            with perf.stage("ledger"):
                self.ledger.mark(op, **fields)
        except JournalError:
            log.warning(f"campaign {self.name!r}: ledger marker {op!r} "
                        "shed (journal full); campaign continues")

    def run(self, max_units: int | None = None,
            progress=None) -> dict:
        """Execute every pending unit to a durable result; returns the
        campaign report (status ``complete`` / ``preempted`` /
        ``paused``). Safe to call again after ANY interruption — a
        completed campaign returns immediately with everything skipped.
        ``progress(unit, result)`` fires after each unit's result is
        durable (the kill drills key their timing on it)."""
        self._install_signals()
        t0 = time.monotonic()
        status = "complete"
        ran = skipped = 0
        try:
            with perf.stage("campaign"):
                with perf.stage("resume"):
                    self._done = self._scan_results()
                    snap, _ = self._latest_snapshot()
                    if self.ledger is None:
                        self.ledger = RequestJournal(self.dir / "ledger",
                                                     fsync_every=1)
                    resumed = (not self._fresh) and (
                        bool(self._done) or snap is not None)
                    if snap is not None:
                        self._prior_wall_s = float(snap.get("wall_s", 0.0))
                    if resumed:
                        perf.add("campaign_resumes")
                        self._mark("resumed", done=len(self._done),
                                   total=len(self.units))
                        flight.note("campaign.resume", name=self.name,
                                    done=len(self._done),
                                    total=len(self.units))
                        degrade.record(
                            "campaign.resumed", self.name,
                            f"campaign resumed with {len(self._done)}/"
                            f"{len(self.units)} units already durable; "
                            "completed units skipped, the remainder "
                            "re-runs — assembly is bitwise-identical to "
                            "an uninterrupted run",
                            fix="none needed — resume IS the designed "
                                "recovery path")
                self._fresh = False
                skipped = len(self._done)
                pending = [u for u in self.units if u.uid not in self._done]
                for u in pending:
                    if self._drain:
                        status = "preempted"
                        break
                    if max_units is not None and ran >= max_units:
                        status = "paused"
                        break
                    fn = resolve_kind(u.kind)
                    tu = time.monotonic()
                    with perf.stage("unit"):
                        result = fn(dict(u.payload))
                    with perf.stage("checkpoint"):
                        _write_checkpoint(
                            self._results_dir / f"{u.uid}.ckpt", result)
                    self._unit_s.append(time.monotonic() - tu)
                    self._done.add(u.uid)
                    ran += 1
                    perf.add("campaign_units_run")
                    self._mark("unit_done", uid=u.uid, kind=u.kind)
                    if progress is not None:
                        progress(u, result)
                    if ran % self.checkpoint_every == 0:
                        with perf.stage("checkpoint"):
                            self._snapshot()
                        self._mark("snapshot", gen=self._gen,
                                   done=len(self._done))
                    # the preemption drill: os._exit(70) AFTER this
                    # unit's result is durable — exactly what a SIGKILL
                    # between checkpoints looks like to the store
                    if faults.trip("campaign.run",
                                   f"unit:{u.uid}") == "kill":
                        os._exit(70)
                with perf.stage("checkpoint"):
                    self._snapshot(status=status)
                self._mark("campaign_status", status=status,
                           done=len(self._done), total=len(self.units))
        finally:
            self._restore_signals()
        wall = time.monotonic() - t0
        report = {
            "name": self.name,
            "status": status,
            "units_total": len(self.units),
            "units_done": len(self._done),
            "units_run": ran,
            "units_skipped": skipped,
            "wall_s": round(wall, 4),
            "snapshot_gen": self._gen,
            "dir": str(self.dir),
        }
        flight.note("campaign.status", **{k: v for k, v in report.items()
                                          if k != "dir"})
        log.info(f"campaign {self.name!r} {status}: "
                 f"{len(self._done)}/{len(self.units)} done "
                 f"({ran} run, {skipped} skipped) in {wall:.2f}s")
        return report

    # -- results -----------------------------------------------------------------

    def results(self) -> dict:
        """uid -> validated durable result, manifest order. Raises
        FileNotFoundError while units are still pending — assembly is
        for finished campaigns (``status == "complete"``)."""
        out = {}
        for u in self.units:
            out[u.uid] = _read_checkpoint(
                self._results_dir / f"{u.uid}.ckpt")
        return out


def campaign_status(dirpath: str | Path) -> dict:
    """Read-only progress probe for ``pint_tpu status --campaign``:
    manifest + newest loadable snapshot + durable-result count, with
    checkpoint age and ETA. Never mutates the store (a corrupt newest
    snapshot is simply skipped here; the runner's resume path is what
    quarantines)."""
    d = Path(dirpath)
    man = json.loads((d / "manifest.json").read_text())
    total = len(man["units"])
    done = len(list((d / "results").glob("*.ckpt"))) \
        if (d / "results").is_dir() else 0
    snap = age = eta = status = None
    snaps = sorted((d / "snapshots").glob("snapshot-*.ckpt"),
                   key=_snap_index, reverse=True) \
        if (d / "snapshots").is_dir() else []
    for p in snaps:
        try:
            snap = _read_checkpoint(p)
        except Exception:  # noqa: BLE001 — read-only probe: skip to the previous generation  # jaxlint: disable=silent-except
            continue
        age = round(max(time.time() - snap.get("t_unix", 0.0), 0.0), 3)
        status = snap.get("status")
        wall = float(snap.get("wall_s", 0.0))
        sdone = len(snap.get("done", ()))
        if 0 < sdone < total and wall > 0:
            eta = round(wall / sdone * (total - sdone), 3)
        break
    events = []
    ledger = d / "ledger"
    if ledger.is_dir():
        records, _ = replay_records(ledger)
        events = [r["op"] for r in records]
    return {
        "name": man["name"],
        "dir": str(d),
        "status": status or ("complete" if done >= total else "unknown"),
        "units_done": done,
        "units_total": total,
        "checkpoint_age_s": age,
        "eta_s": 0.0 if done >= total else eta,
        "resumes": events.count("resumed"),
        "ledger_events": len(events),
    }
