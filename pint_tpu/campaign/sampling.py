"""Built-in campaign unit kinds: the chain/sweep surfaces as work units.

Each kind is one deterministic computation keyed ENTIRELY by its
payload — that is the contract that makes campaign resume bitwise-exact
(campaign/runner.py): re-running a lost unit from its payload rebuilds
the identical result, so assembled output never depends on where a
preemption landed.

- ``demo.stretch_chain`` — one affine-invariant stretch-move chain over
  a small correlated Gaussian posterior. Self-contained (no reference
  data, no network), per-chain keys via ``fold_in(seed, chain_id)`` —
  the tier-1 kill drill and the docs walkthrough run campaigns of
  these.
- ``noise.sample_chain`` — one chain of a real
  :class:`~pint_tpu.fitting.noise_like.MarginalizedPosterior` (or any
  factory returning an object with ``.sample``), via
  ``post.sample(chain_ids=[c])`` — the per-chain determinism that API
  already locks (fleet ≡ solo per chain id) is what the campaign
  inherits.
- ``grid.eval`` — one point of a grid scan: an importable
  ``module:function`` applied to the point's coordinates.

Factories named by ``noise.sample_chain`` payloads are memoized
per-process (building a posterior is expensive; every chain unit of a
campaign shares one), keyed by the factory string + canonical kwargs.
"""

from __future__ import annotations

import hashlib
import importlib
import json

import numpy as np

from pint_tpu.campaign.runner import WorkUnit, register_kind, work_unit

__all__ = ["chain_units", "grid_units", "result_digest"]


# -- demo.stretch_chain -------------------------------------------------------------

def _demo_lnpost(ndim: int):
    """A correlated Gaussian log-posterior (the walkthrough target):
    banded precision, deterministic in ndim only."""
    import jax.numpy as jnp

    prec = np.eye(ndim) + 0.4 * (np.eye(ndim, k=1) + np.eye(ndim, k=-1))
    prec_j = jnp.asarray(prec)

    def lnpost(x):
        return -0.5 * x @ prec_j @ x

    return lnpost


@register_kind("demo.stretch_chain")
def _run_demo_chain(payload: dict) -> dict:
    """One stretch-move chain: ``{"chain_id", "seed", "nsteps",
    "ndim", "walkers"}`` -> the chain's full output as numpy arrays.
    Key and starts derive from (seed, chain_id) exactly as
    MarginalizedPosterior._chain_starts does — chain c is the same
    bits whether run solo, in a fleet, or re-run after a kill."""
    import jax
    import jax.numpy as jnp

    from pint_tpu.sampler import make_stretch_chain

    cid = int(payload["chain_id"])
    seed = int(payload["seed"])
    ndim = int(payload.get("ndim", 3))
    nw = int(payload.get("walkers", 8))
    nsteps = int(payload.get("nsteps", 50))

    chain = jax.jit(make_stretch_chain(_demo_lnpost(ndim), nsteps))
    key = jax.random.fold_in(jax.random.PRNGKey(seed), cid)
    rng = np.random.default_rng(seed * 100003 + cid)
    x0 = jnp.asarray(rng.normal(size=(nw, ndim)) * 0.5)
    out = chain(x0, key)
    return {"chain_id": cid,
            "samples": np.asarray(out["samples"]),
            "lnpost": np.asarray(out["lnpost"]),
            "accept": np.asarray(out["accept"])}


# -- noise.sample_chain -------------------------------------------------------------

_FACTORY_MEMO: dict = {}


def _factory_result(entry: str, kwargs: dict):
    key = (entry, json.dumps(kwargs, sort_keys=True, default=str))
    if key not in _FACTORY_MEMO:
        mod, _, attr = entry.partition(":")
        _FACTORY_MEMO[key] = getattr(importlib.import_module(mod),
                                     attr)(**kwargs)
    return _FACTORY_MEMO[key]


@register_kind("noise.sample_chain")
def _run_noise_chain(payload: dict) -> dict:
    """One chain of a factory-built posterior: ``{"factory":
    "module:function", "factory_kwargs": {...}, "chain_id": c}`` plus
    optional ``sample_kwargs`` forwarded to ``.sample``. The factory is
    memoized per-process; the chain itself is ``sample(chain_ids=[c])``
    — bitwise per-chain by the fleet-determinism contract."""
    post = _factory_result(payload["factory"],
                           dict(payload.get("factory_kwargs", {})))
    cid = int(payload["chain_id"])
    out = post.sample(chain_ids=[cid],
                      **dict(payload.get("sample_kwargs", {})))
    return {"chain_id": cid,
            **{k: np.asarray(v) for k, v in out.items()
               if not k.startswith("_")}}


# -- grid.eval ----------------------------------------------------------------------

@register_kind("grid.eval")
def _run_grid_point(payload: dict) -> dict:
    """One grid-scan point: ``{"fn": "module:function", "point":
    {...}}`` -> ``{"point", "value"}``. The function must be pure in
    the point (seeds, if any, ride inside it)."""
    mod, _, attr = payload["fn"].partition(":")
    fn = getattr(importlib.import_module(mod), attr)
    value = fn(**dict(payload["point"]))
    return {"point": dict(payload["point"]),
            "value": np.asarray(value)}


# -- unit factories -----------------------------------------------------------------

def chain_units(nchains: int, seed: int, *, kind: str = "demo.stretch_chain",
                **payload) -> list[WorkUnit]:
    """One unit per chain id, the campaign shape for sampling runs."""
    return [work_unit(kind, chain_id=c, seed=seed, **payload)
            for c in range(nchains)]


def grid_units(fn: str, points: list[dict]) -> list[WorkUnit]:
    """One unit per grid point for an importable ``module:function``."""
    return [work_unit("grid.eval", fn=fn, point=p) for p in points]


# -- assembly -----------------------------------------------------------------------

def result_digest(results: dict) -> str:
    """sha256 over the raw bytes of every array in every result, in
    manifest order — the bitwise-resume witness: a resumed campaign and
    its uninterrupted twin must produce the SAME digest."""
    h = hashlib.sha256()
    for uid in results:
        h.update(uid.encode())
        r = results[uid]
        for k in sorted(r):
            v = r[k]
            h.update(k.encode())
            if isinstance(v, np.ndarray):
                h.update(np.ascontiguousarray(v).tobytes())
            else:
                h.update(json.dumps(v, sort_keys=True,
                                    default=str).encode())
    return h.hexdigest()
