"""Preemption-safe campaigns: durable, resumable long-running device work.

See campaign/runner.py for the durability + bitwise-resume contract and
campaign/sampling.py for the built-in unit kinds.
"""

from pint_tpu.campaign.runner import (CampaignRunner, WorkUnit,
                                      campaign_status, content_key,
                                      register_kind, resolve_kind,
                                      work_unit)
from pint_tpu.campaign.sampling import (chain_units, grid_units,
                                        result_digest)

__all__ = ["CampaignRunner", "WorkUnit", "campaign_status", "chain_units",
           "content_key", "grid_units", "register_kind", "resolve_kind",
           "result_digest", "work_unit"]
