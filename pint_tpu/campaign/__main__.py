"""Campaign CLI: run a self-contained demo campaign, or probe progress.

``python -m pint_tpu.campaign --dir D --demo-chains 4 --steps 60 --seed 7``
runs (or RESUMES — the same command line is both) a demo stretch-move
campaign in ``D``, printing machine-parseable progress:

- ``UNIT::<uid>`` after each unit's result is durable — the tier-1
  kill drill SIGKILLs the process on the first of these, exactly
  between checkpoints;
- ``RESULT::{json}`` at exit: status, done/total, the bitwise digest
  over every assembled result array (resume parity locks on it), the
  campaign perf breakdown (attribution >= 90% named), degradation
  kinds and ledger ops.

``--status`` prints the read-only :func:`campaign_status` probe
instead (what ``pint_tpu status --campaign`` wraps).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="pint_tpu.campaign")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--status", action="store_true",
                    help="print the read-only progress probe and exit")
    ap.add_argument("--demo-chains", type=int, default=4)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--walkers", type=int, default=8)
    ap.add_argument("--ndim", type=int, default=3)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--checkpoint-every", type=int, default=None)
    ap.add_argument("--unit-sleep", type=float, default=0.0,
                    help="stall this many seconds after each durable "
                         "unit (the SIGKILL drill kills into the stall "
                         "so the kill provably lands BETWEEN checkpoints)")
    args = ap.parse_args(argv)

    from pint_tpu.campaign import (CampaignRunner, campaign_status,
                                   chain_units, result_digest)

    if args.status:
        print(json.dumps(campaign_status(args.dir), indent=1))
        return 0

    from pint_tpu.ops import degrade, perf

    units = chain_units(args.demo_chains, args.seed, nsteps=args.steps,
                        walkers=args.walkers, ndim=args.ndim)
    runner = CampaignRunner(args.dir, units, name="demo",
                            checkpoint_every=args.checkpoint_every)

    def _progress(u, result):
        print(f"UNIT::{u.uid}", flush=True)
        if args.unit_sleep > 0:
            import time

            time.sleep(args.unit_sleep)

    with perf.collect() as rep:
        report = runner.run(progress=_progress)
    out = dict(report)
    out["breakdown"] = perf.campaign_breakdown(rep)
    out["degradations"] = sorted({e.kind for e in degrade.events()})
    if report["status"] == "complete":
        out["digest"] = result_digest(runner.results())
    status = campaign_status(args.dir)
    out["ledger_events"] = status["ledger_events"]
    out["resumes"] = status["resumes"]
    print("RESULT::" + json.dumps(out, default=float), flush=True)
    return 0 if report["status"] in ("complete", "preempted",
                                     "paused") else 1


if __name__ == "__main__":
    sys.exit(main())
